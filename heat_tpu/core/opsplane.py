"""Live ops plane: a streaming metrics registry, a stdlib HTTP ops
endpoint, and multi-window SLO burn-rate alerting.

Everything the runtime already measures — SLO histograms
(``core/health_runtime.py``), admission/billing state (``core/serving.py``),
memory watermarks (``core/memledger.py``), numerics drift
(``core/numlens.py``), reform counters (``core/elastic.py``), the program
cache (``core/fusion.py``) — is in-process and post-hoc: ``report()``, CLI
verbs, flight bundles. This module is the live tap over those SAME gauges:

**The registry + sampler.** :func:`collect` projects the existing gauges
into a flat sample list ``(name, labels, value)`` — counters, gauges and
one real log-bucketed latency histogram — and a fixed-cadence daemon
sampler (``HEAT_TPU_OPS_INTERVAL_S``, default 2s) folds every sample into a
bounded time-series registry (:func:`series`), the stream ROADMAP item 6's
autoscaler consumes. No new instrumentation seams: collection is pure
module-state reads — it never forces a pending chain and never initializes
the backend.

**The ops server.** ``HEAT_TPU_OPS_PORT`` (off by default; ``0`` = an
ephemeral port) arms a stdlib ``ThreadingHTTPServer`` serving

- ``/metrics`` — Prometheus text exposition (``# HELP``/``# TYPE``,
  per-tenant and per-program-key labels),
- ``/healthz`` — liveness: watchdog never tripped, no active burn alert,
- ``/readyz`` — readiness: healthy AND mesh up AND admission not saturated,
- ``/debug/report`` — the full ``telemetry.report()`` as JSON,
- ``/debug/trace`` — the live trace-event export (``?analyze=1`` runs
  ``tracelens.analyze`` over it),
- ``/debug/flight`` — an on-demand flight-recorder dump,
- ``/debug/numerics`` — the numerics-lens ledger,

so a serving process is inspectable mid-traffic without touching client
threads. Scrapes run on server daemon threads against pure state.

**Burn-rate alerting.** Multi-window SLO burn over the rolling breach
windows ``health_runtime`` already keeps (now tenant-tagged via serving's
``_TENANT_HOOK``): per metric (sync/dispatch/compile), per tenant and
global (``tenant="*"``), burn = (breach fraction in window) / error budget
where the budget is ``1 - HEAT_TPU_SLO_TARGET``. An alert fires when BOTH
the fast window (``HEAT_TPU_SLO_FAST_S``) and the slow window
(``HEAT_TPU_SLO_SLOW_S``) burn at ``HEAT_TPU_SLO_BURN``× or faster — the
classic two-window page that ignores blips (fast-only) and stale history
(slow-only). Rising edges emit an ``slo_burn`` telemetry event and a
bounded finding (:func:`burn_findings`); falling edges emit
``slo_burn_clear``. Alert state is exported on ``/metrics``
(``heat_tpu_slo_burn_alert``) and degrades ``/healthz``.

Env knobs follow the ``HEAT_TPU_MEMORY_BUDGET`` convention: malformed
values warn and disarm, never crash an import. ``telemetry.reset()``
cascades here — series, burn alerts, findings and scrape counters clear;
configuration and an armed server survive.
"""

from __future__ import annotations

import json
import math
import os
import re
import threading
import time
import warnings
from collections import OrderedDict, deque
from typing import Any, Dict, Iterable, List, Optional, Tuple

from . import health_runtime, telemetry

__all__ = [
    "collect",
    "render",
    "validate_exposition",
    "schema",
    "sample",
    "series",
    "set_burn",
    "on_burn",
    "burn_report",
    "burn_findings",
    "health_status",
    "ready_status",
    "serve",
    "shutdown",
    "status",
    "reset",
]


# ----------------------------------------------------------------------
# env knobs (warn-and-disarm, the HEAT_TPU_MEMORY_BUDGET convention)
# ----------------------------------------------------------------------
def _env_float(name: str, default: float, lo: float, hi: float) -> float:
    raw = os.environ.get(name)
    if raw is None or not raw.strip():
        return default
    try:
        v = float(raw)
        if not (lo <= v <= hi) or math.isnan(v):
            raise ValueError(f"out of range [{lo}, {hi}]")
        return v
    except ValueError as exc:
        warnings.warn(
            f"{name}={raw!r} is not a valid value ({exc}); "
            f"using the default {default}",
            stacklevel=2,
        )
        return default


def _env_port() -> Optional[int]:
    """``HEAT_TPU_OPS_PORT``: unset/empty = ops server off (the default);
    ``0`` = arm on an ephemeral port; malformed warns and disarms."""
    raw = os.environ.get("HEAT_TPU_OPS_PORT")
    if raw is None or not raw.strip():
        return None
    try:
        port = int(raw)
        if not (0 <= port <= 65535):
            raise ValueError("out of range [0, 65535]")
        return port
    except ValueError as exc:
        warnings.warn(
            f"HEAT_TPU_OPS_PORT={raw!r} is not a valid port ({exc}); "
            "the ops server stays disarmed",
            stacklevel=2,
        )
        return None


_INTERVAL_S = _env_float("HEAT_TPU_OPS_INTERVAL_S", 2.0, 0.05, 3600.0)
_RETAIN = int(_env_float("HEAT_TPU_OPS_RETAIN", 512, 8, 65536))
#: distinct (name, labels) series kept; past the cap new series are dropped
#: and counted — the registry must stay O(1) however hot the label churn
_SERIES_CAP = 4096

# ----------------------------------------------------------------------
# metric-name schema: the exporter contract dashboards pin against.
# doc/metrics_schema.json is the committed copy; tests diff the two so a
# rename/removal fails CI instead of silently breaking a dashboard.
# ----------------------------------------------------------------------
_C, _G, _H = "counter", "gauge", "histogram"
SCHEMA: "OrderedDict[str, Dict[str, Any]]" = OrderedDict(
    [
        # -- ops-plane self metrics ------------------------------------
        ("heat_tpu_up", (_G, "Always 1 while the process is scrapable.", [])),
        ("heat_tpu_mesh_up", (_G, "1 once the device mesh is initialized.", [])),
        ("heat_tpu_ops_samples_total", (_C, "Registry sampler ticks.", [])),
        ("heat_tpu_ops_scrapes_total", (_C, "HTTP scrapes served, by endpoint.", ["endpoint"])),
        ("heat_tpu_ops_scrape_errors_total", (_C, "HTTP scrapes that failed.", [])),
        ("heat_tpu_ops_series", (_G, "Live time-series in the registry.", [])),
        ("heat_tpu_ops_series_dropped_total", (_C, "Series dropped past the registry cap.", [])),
        ("heat_tpu_ops_sample_ms", (_G, "Wall time of the last registry sample tick.", [])),
        # -- telemetry counters ----------------------------------------
        ("heat_tpu_collectives_total", (_C, "Collective operations recorded, by op.", ["op"])),
        ("heat_tpu_timeline_events", (_G, "Telemetry timeline events currently buffered.", [])),
        ("heat_tpu_timeline_events_dropped_total", (_C, "Timeline events dropped past the cap.", [])),
        ("heat_tpu_nonfinite_total", (_C, "Non-finite detections, by kind.", ["kind"])),
        # -- fusion program cache --------------------------------------
        ("heat_tpu_fusion_compiles_total", (_C, "Fused-program compiles (retraces).", [])),
        ("heat_tpu_fusion_hits_total", (_C, "In-memory program-cache hits.", [])),
        ("heat_tpu_fusion_disk_hits_total", (_C, "Persistent-cache warm starts.", [])),
        ("heat_tpu_fusion_forces_total", (_C, "Chain forces.", [])),
        ("heat_tpu_fusion_evictions_total", (_C, "LRU program evictions.", [])),
        ("heat_tpu_fusion_degraded_total", (_C, "Programs degraded to per-op replay.", [])),
        ("heat_tpu_fusion_quarantine_hits_total", (_C, "Forces that skipped a quarantined compile.", [])),
        ("heat_tpu_fusion_cache_size", (_G, "Compiled programs currently cached.", [])),
        ("heat_tpu_fusion_quarantined", (_G, "Program keys currently quarantined.", [])),
        # -- latency (health_runtime histograms; key = program key or
        # sync trigger, LRU-capped at health_runtime._PROGRAM_CAP) ------
        ("heat_tpu_latency_seconds", (_H, "Operation latency, by metric (sync/dispatch/compile).", ["metric"])),
        ("heat_tpu_latency_count_total", (_C, "Latency observations, by metric and key.", ["metric", "key"])),
        ("heat_tpu_latency_p50_ms", (_G, "Rolling p50 latency, by metric and key.", ["metric", "key"])),
        ("heat_tpu_latency_p99_ms", (_G, "Rolling p99 latency, by metric and key.", ["metric", "key"])),
        # -- SLO gauges + burn-rate alerting ---------------------------
        ("heat_tpu_slo_limit_ms", (_G, "Configured SLO limit (absent metric = no SLO).", ["metric"])),
        ("heat_tpu_slo_window_p99_ms", (_G, "p99 over the rolling SLO window.", ["metric"])),
        ("heat_tpu_slo_ok_ratio", (_G, "In-SLO fraction over the rolling window.", ["metric"])),
        ("heat_tpu_slo_breaches_total", (_C, "SLO breaches since reset.", ["metric"])),
        ("heat_tpu_slo_burn_rate", (_G, "Error-budget burn rate, by window (fast/slow).", ["metric", "tenant", "window"])),
        ("heat_tpu_slo_burn_alert", (_G, "1 while the two-window burn alert is firing.", ["metric", "tenant"])),
        ("heat_tpu_slo_burn_alerts_total", (_C, "Burn-alert rising edges.", ["metric", "tenant"])),
        # -- watchdog + flight recorder --------------------------------
        ("heat_tpu_watchdog_trips_total", (_C, "Watchdog deadline trips.", [])),
        ("heat_tpu_watchdog_armed", (_G, "Collectives currently under watchdog guard.", [])),
        ("heat_tpu_flight_events", (_G, "Flight-recorder ring occupancy.", [])),
        ("heat_tpu_flight_dropped_total", (_C, "Flight events dropped past the ring cap.", [])),
        ("heat_tpu_flight_dumps_total", (_C, "Flight bundles written.", [])),
        # -- memory ledger ---------------------------------------------
        ("heat_tpu_mem_watermark_bytes", (_G, "High watermark of sampled live bytes.", [])),
        ("heat_tpu_mem_budget_bytes", (_G, "Resolved memory budget (absent = disarmed).", [])),
        ("heat_tpu_mem_gate_total", (_C, "Admission-gate outcomes, by outcome.", ["outcome"])),
        # -- numerics lens ---------------------------------------------
        ("heat_tpu_numerics_dispatches_sampled_total", (_C, "Dispatches the numerics lens sampled.", [])),
        ("heat_tpu_numerics_findings", (_G, "Open numerics findings.", [])),
        # -- multi-process runtime (lease heartbeats + named barriers) -
        ("heat_tpu_peers_expected", (_G, "Controller processes in the current world.", [])),
        ("heat_tpu_peers_lost", (_G, "Peer processes currently declared lost.", [])),
        ("heat_tpu_peer_heartbeats_total", (_C, "Lease heartbeats written.", [])),
        ("heat_tpu_peer_heartbeat_errors_total", (_C, "Lease beats that failed to write (missed beats).", [])),
        ("heat_tpu_barriers_total", (_C, "Named cross-process barrier waits entered.", [])),
        ("heat_tpu_barrier_timeouts_total", (_C, "Barriers abandoned on timeout (StallError).", [])),
        ("heat_tpu_barrier_threads_abandoned", (_G, "Abandoned barrier daemon threads still alive.", [])),
        # -- elastic supervisor ----------------------------------------
        ("heat_tpu_elastic_total", (_C, "Elastic supervisor events, by event.", ["event"])),
        ("heat_tpu_elastic_downtime_ms_total", (_C, "Cumulative drain-to-restore wall time.", [])),
        # -- serving sessions (tenant = session name) ------------------
        ("heat_tpu_sessions_active", (_G, "Serving sessions currently entered.", [])),
        ("heat_tpu_session_dispatches_total", (_C, "Fused dispatches billed, by tenant.", ["tenant"])),
        ("heat_tpu_session_roots_total", (_C, "Chain roots billed, by tenant.", ["tenant"])),
        ("heat_tpu_session_compiles_total", (_C, "Compiles billed, by tenant.", ["tenant"])),
        ("heat_tpu_session_incidents_total", (_C, "Contained incidents, by tenant and kind.", ["tenant", "kind"])),
        ("heat_tpu_session_admission_waits_total", (_C, "Dispatches that waited for admission, by tenant.", ["tenant"])),
        ("heat_tpu_session_admission_waited_seconds_total", (_C, "Seconds spent waiting for admission, by tenant.", ["tenant"])),
        # -- admission token buckets -----------------------------------
        ("heat_tpu_admission_tokens", (_G, "Projected tokens available, by bucket.", ["bucket"])),
        ("heat_tpu_admission_admitted_total", (_C, "Dispatches admitted, by bucket.", ["bucket"])),
        ("heat_tpu_admission_refused_total", (_C, "Dispatches refused, by bucket.", ["bucket"])),
        # -- autoscale controller (ROADMAP item 6: the closed loop) ----
        ("heat_tpu_autoscale_armed", (_G, "1 while the autoscale controller is armed.", [])),
        ("heat_tpu_autoscale_shedding", (_G, "1 while tiered load shedding is active.", [])),
        ("heat_tpu_autoscale_mesh_devices", (_G, "Devices in the current (possibly shrunk) mesh.", [])),
        ("heat_tpu_autoscale_mesh_baseline", (_G, "Devices in the full pre-shrink mesh.", [])),
        ("heat_tpu_autoscale_decisions_total", (_C, "Controller decisions, by action.", ["action"])),
        ("heat_tpu_autoscale_shed_refusals_total", (_C, "Dispatches shed from shed-tier sessions.", [])),
    ]
)


def schema() -> Dict[str, Dict[str, Any]]:
    """The exporter contract: ``{name: {"type", "help", "labels"}}`` — the
    committed ``doc/metrics_schema.json`` must equal this exactly."""
    return {
        name: {"type": mtype, "help": help_, "labels": list(labels)}
        for name, (mtype, help_, labels) in SCHEMA.items()
    }


#: serving sessions exported per scrape (newest first) — the tenant-label
#: cardinality cap, mirroring fusion._PROGRAM_INFO's LRU for program keys
_TENANT_CAP = 64

_INCIDENT_KINDS = (
    ("degraded", "degraded"),
    ("quarantine_hits", "quarantine_hit"),
    ("mem_refused", "mem_refused"),
    ("admission_refused", "admission_refused"),
    ("shed", "shed"),
)


# ----------------------------------------------------------------------
# collection: the existing gauges, projected flat. Pure module-state
# reads — never forces a chain, never initializes the backend; every
# subsystem is wrapped so one broken block never drops the whole scrape.
# ----------------------------------------------------------------------
Sample = Tuple[str, Dict[str, str], float]


def _mesh_up() -> bool:
    try:
        from . import communication

        return communication.MESH_WORLD is not None
    except Exception:  # pragma: no cover - import-order safety only
        return False


def _collect_telemetry(out: List[Sample]) -> None:
    st = telemetry._GLOBAL
    for op, rec in list(st.collectives.items()):
        out.append(("heat_tpu_collectives_total", {"op": str(op)}, float(rec["count"])))
    out.append(("heat_tpu_timeline_events", {}, float(len(st.events))))
    out.append(("heat_tpu_timeline_events_dropped_total", {}, float(st.events_dropped)))
    for kind, n in list(st.nonfinite.items()):
        out.append(("heat_tpu_nonfinite_total", {"kind": str(kind)}, float(n)))


def _collect_fusion(out: List[Sample]) -> None:
    from . import fusion

    stats = fusion.cache_stats()
    for field in (
        "compiles", "hits", "disk_hits", "forces", "evictions", "degraded",
        "quarantine_hits",
    ):
        out.append((f"heat_tpu_fusion_{field}_total", {}, float(stats[field])))
    out.append(("heat_tpu_fusion_cache_size", {}, float(stats["size"])))
    out.append(("heat_tpu_fusion_quarantined", {}, float(stats["quarantined"])))


def _collect_health(out: List[Sample]) -> None:
    wd = health_runtime.watchdog_stats()
    out.append(("heat_tpu_watchdog_trips_total", {}, float(wd["trips"])))
    out.append(("heat_tpu_watchdog_armed", {}, float(wd["armed"])))
    fl = health_runtime.flight_stats()
    out.append(("heat_tpu_flight_events", {}, float(fl.get("events", 0))))
    out.append(("heat_tpu_flight_dropped_total", {}, float(fl.get("dropped", 0))))
    out.append(("heat_tpu_flight_dumps_total", {}, float(fl.get("dumps", 0))))
    st = health_runtime._H_GLOBAL
    for metric in health_runtime._METRICS:
        tables = {"*": st.overall[metric]}
        tables.update(getattr(st, metric))
        for key, hist in tables.items():
            if not hist.count:
                continue
            labels = {"metric": metric, "key": str(key)}
            out.append(("heat_tpu_latency_count_total", labels, float(hist.count)))
            out.append(
                ("heat_tpu_latency_p50_ms", labels, round(hist.percentile(50.0) * 1e3, 6))
            )
            out.append(
                ("heat_tpu_latency_p99_ms", labels, round(hist.percentile(99.0) * 1e3, 6))
            )
    slo = health_runtime._slo_block()
    for metric in health_runtime._METRICS:
        entry = slo.get(metric) or {}
        if entry.get("limit_ms") is not None:
            out.append(("heat_tpu_slo_limit_ms", {"metric": metric}, float(entry["limit_ms"])))
        if entry.get("window_p99_ms") is not None:
            out.append(
                ("heat_tpu_slo_window_p99_ms", {"metric": metric}, float(entry["window_p99_ms"]))
            )
        if entry.get("ok_ratio") is not None:
            out.append(("heat_tpu_slo_ok_ratio", {"metric": metric}, float(entry["ok_ratio"])))
        out.append(
            ("heat_tpu_slo_breaches_total", {"metric": metric}, float(entry.get("breaches_total", 0)))
        )


def _collect_memory(out: List[Sample]) -> None:
    from . import memledger

    wm = memledger.watermark()
    out.append(("heat_tpu_mem_watermark_bytes", {}, float(wm["bytes"])))
    info = memledger.budget_info(resolve=False)  # resolve=True probes devices
    if isinstance(info.get("budget_bytes"), int):
        out.append(("heat_tpu_mem_budget_bytes", {}, float(info["budget_bytes"])))
    for outcome in ("checks", "allowed", "exceeded", "warned", "raised", "drains"):
        if outcome in info:
            out.append(("heat_tpu_mem_gate_total", {"outcome": outcome}, float(info[outcome])))


def _collect_numerics(out: List[Sample]) -> None:
    from . import numlens

    out.append(
        ("heat_tpu_numerics_dispatches_sampled_total", {}, float(numlens._SAMPLED))
    )
    out.append(("heat_tpu_numerics_findings", {}, float(len(numlens.findings()))))


def _collect_elastic(out: List[Sample]) -> None:
    hook = telemetry._ELASTIC_HOOK
    if hook is None:
        return
    stats = hook()
    for event in (
        "preemptions", "reforms", "failed_reforms", "steps_replayed",
        "checkpoints", "drained_roots", "peer_losses",
    ):
        if event in stats:
            out.append(("heat_tpu_elastic_total", {"event": event}, float(stats[event])))
    out.append(("heat_tpu_elastic_downtime_ms_total", {}, float(stats["downtime_ms"])))


def _collect_multihost(out: List[Sample]) -> None:
    # set-attribute hook (the _ELASTIC_HOOK pattern): core/multihost.py
    # installs report_stats on telemetry at import
    hook = telemetry._MULTIHOST_HOOK
    if hook is None:
        return
    st = hook()
    out.append(("heat_tpu_peers_expected", {}, float(st.get("world", 1))))
    out.append(("heat_tpu_peers_lost", {}, float(len(st.get("peers_lost") or ()))))
    out.append(("heat_tpu_peer_heartbeats_total", {}, float(st.get("heartbeats", 0))))
    out.append(
        ("heat_tpu_peer_heartbeat_errors_total", {}, float(st.get("heartbeat_errors", 0)))
    )
    out.append(("heat_tpu_barriers_total", {}, float(st.get("barriers", 0))))
    out.append(
        ("heat_tpu_barrier_timeouts_total", {}, float(st.get("barrier_timeouts", 0)))
    )
    out.append(
        ("heat_tpu_barrier_threads_abandoned", {}, float(st.get("abandoned_alive", 0)))
    )


def _bucket_tokens(bucket) -> float:
    """A bucket's projected token count WITHOUT taking one: the refill math
    from ``_TokenBucket.take``, read under its lock."""
    with bucket._lock:
        now = time.monotonic()
        return min(bucket.burst, bucket.tokens + (now - bucket.ts) * bucket.rate)


def _bucket_samples(out: List[Sample], name: str, bucket) -> None:
    labels = {"bucket": name}
    out.append(("heat_tpu_admission_tokens", labels, round(_bucket_tokens(bucket), 3)))
    out.append(("heat_tpu_admission_admitted_total", labels, float(bucket.admitted)))
    out.append(("heat_tpu_admission_refused_total", labels, float(bucket.refused)))


def _collect_serving(out: List[Sample]) -> None:
    from . import serving

    with serving._LOCK:
        sessions = list(serving._SESSIONS.values())
        active = serving._ACTIVE
        global_bucket = serving._GLOBAL_BUCKET
    out.append(("heat_tpu_sessions_active", {}, float(active)))
    if global_bucket is not None:
        _bucket_samples(out, "global", global_bucket)
    # newest sessions win the label budget (the tenant-cardinality cap)
    for sess in sessions[-_TENANT_CAP:]:
        tenant = {"tenant": sess.name}
        stats = dict(sess.stats)
        out.append(("heat_tpu_session_dispatches_total", tenant, float(stats["dispatches"])))
        out.append(("heat_tpu_session_roots_total", tenant, float(stats["roots"])))
        out.append(("heat_tpu_session_compiles_total", tenant, float(stats["compiles"])))
        for field, kind in _INCIDENT_KINDS:
            out.append(
                (
                    "heat_tpu_session_incidents_total",
                    {"tenant": sess.name, "kind": kind},
                    float(stats[field]),
                )
            )
        out.append(
            ("heat_tpu_session_admission_waits_total", tenant, float(stats["admission_waits"]))
        )
        out.append(
            (
                "heat_tpu_session_admission_waited_seconds_total",
                tenant,
                round(float(stats["admission_waited_s"]), 6),
            )
        )
        if sess.bucket is not None:
            _bucket_samples(out, f"session:{sess.name}", sess.bucket)


def _collect_autoscale(out: List[Sample]) -> None:
    # set-attribute hook (the _ELASTIC_HOOK pattern): core/autoscale.py
    # installs its stats() on telemetry at import, so this module never
    # imports the controller that imports it back
    hook = telemetry._AUTOSCALE_HOOK
    if hook is None:
        return
    st = hook()
    out.append(("heat_tpu_autoscale_armed", {}, 1.0 if st.get("armed") else 0.0))
    out.append(
        ("heat_tpu_autoscale_shedding", {}, 1.0 if st.get("shedding") else 0.0)
    )
    mesh = st.get("mesh") or {}
    if mesh.get("devices"):
        out.append(("heat_tpu_autoscale_mesh_devices", {}, float(mesh["devices"])))
    if mesh.get("baseline"):
        out.append(("heat_tpu_autoscale_mesh_baseline", {}, float(mesh["baseline"])))
    for action, n in sorted((st.get("decisions") or {}).items()):
        out.append(
            ("heat_tpu_autoscale_decisions_total", {"action": str(action)}, float(n))
        )
    out.append(
        ("heat_tpu_autoscale_shed_refusals_total", {}, float(st.get("shed_refusals", 0)))
    )


def _collect_burn(out: List[Sample]) -> None:
    with _BURN_LOCK:
        for (metric, tenant), row in _ALERTS.items():
            labels = {"metric": metric, "tenant": tenant}
            for window in ("fast", "slow"):
                out.append(
                    (
                        "heat_tpu_slo_burn_rate",
                        dict(labels, window=window),
                        round(row[f"{window}_burn"], 4),
                    )
                )
            out.append(("heat_tpu_slo_burn_alert", labels, 1.0 if row["active"] else 0.0))
            out.append(("heat_tpu_slo_burn_alerts_total", labels, float(row["fired"])))


def _collect_self(out: List[Sample]) -> None:
    out.append(("heat_tpu_up", {}, 1.0))
    out.append(("heat_tpu_mesh_up", {}, 1.0 if _mesh_up() else 0.0))
    out.append(("heat_tpu_ops_samples_total", {}, float(_OPS_STATS["samples"])))
    for endpoint, n in list(_SCRAPES.items()):
        out.append(("heat_tpu_ops_scrapes_total", {"endpoint": endpoint}, float(n)))
    out.append(("heat_tpu_ops_scrape_errors_total", {}, float(_OPS_STATS["scrape_errors"])))
    with _SERIES_LOCK:
        live = len(_SERIES)
    out.append(("heat_tpu_ops_series", {}, float(live)))
    out.append(("heat_tpu_ops_series_dropped_total", {}, float(_OPS_STATS["series_dropped"])))
    out.append(("heat_tpu_ops_sample_ms", {}, float(_OPS_STATS["sample_ms"])))


_COLLECTORS = (
    _collect_self,
    _collect_telemetry,
    _collect_fusion,
    _collect_health,
    _collect_burn,
    _collect_memory,
    _collect_numerics,
    _collect_elastic,
    _collect_serving,
    _collect_autoscale,
    _collect_multihost,
)


def collect() -> List[Sample]:
    """One flat snapshot of every exported gauge: ``(name, labels, value)``
    triples, schema-checked names only. Pure module state — safe from any
    thread, with chains pending, before the backend exists."""
    out: List[Sample] = []
    for collector in _COLLECTORS:
        try:
            collector(out)
        # one broken subsystem must never drop the whole scrape
        except Exception:  # noqa: BLE001
            _OPS_STATS["collect_errors"] += 1
    return out


# ----------------------------------------------------------------------
# the time-series registry + the fixed-cadence sampler
# ----------------------------------------------------------------------
_SERIES: "OrderedDict[Tuple[str, Tuple[Tuple[str, str], ...]], deque]" = OrderedDict()
_SERIES_LOCK = threading.Lock()
_OPS_STATS = {
    "samples": 0,
    "scrape_errors": 0,
    "collect_errors": 0,
    "series_dropped": 0,
    "sample_ms": 0.0,
    "callback_errors": 0,
}
_SCRAPES: Dict[str, int] = {}


def _series_key(name: str, labels: Dict[str, str]) -> Tuple[str, Tuple[Tuple[str, str], ...]]:
    return (name, tuple(sorted(labels.items())))


def sample(now: Optional[float] = None) -> int:
    """One sampler tick: update the burn tracker, collect every gauge and
    fold the values into the bounded time-series registry. Returns the
    number of samples folded. Called at cadence by the daemon sampler and
    by every ``/metrics`` scrape (so alert state is never staler than one
    scrape)."""
    t0 = time.perf_counter()
    _burn_tick(now)
    samples = collect()
    ts = time.time()
    with _SERIES_LOCK:
        for name, labels, value in samples:
            key = _series_key(name, labels)
            dq = _SERIES.get(key)
            if dq is None:
                if len(_SERIES) >= _SERIES_CAP:
                    _OPS_STATS["series_dropped"] += 1
                    continue
                dq = _SERIES[key] = deque(maxlen=_RETAIN)
            dq.append((ts, value))
    _OPS_STATS["samples"] += 1
    _OPS_STATS["sample_ms"] = round((time.perf_counter() - t0) * 1e3, 3)
    return len(samples)


def series(name: str, labels: Optional[Dict[str, str]] = None) -> List[Tuple[float, float]]:
    """The retained ``(unix_ts, value)`` points for one series — the pull
    surface the autoscaler (ROADMAP item 6) reads. ``labels=None`` with a
    single matching series returns it; ambiguity raises."""
    with _SERIES_LOCK:
        if labels is not None:
            dq = _SERIES.get(_series_key(name, labels))
            return list(dq) if dq is not None else []
        matches = [k for k in _SERIES if k[0] == name]
        if not matches:
            return []
        if len(matches) > 1:
            raise ValueError(
                f"{name} has {len(matches)} label sets — pass labels= to pick one"
            )
        return list(_SERIES[matches[0]])


class _Sampler:
    """The fixed-cadence registry pump (daemon thread, like telemetry's
    ``_MetricsSink``): one :func:`sample` every ``interval`` seconds."""

    def __init__(self, interval: float):
        self.interval = max(0.05, float(interval))
        self._stop = threading.Event()
        self._thread = threading.Thread(
            target=self._loop, name="heat-tpu-ops-sampler", daemon=True
        )

    def start(self) -> None:
        self._thread.start()

    def _loop(self) -> None:
        while not self._stop.wait(self.interval):
            try:
                sample()
            # the sampler must outlive any one broken subsystem
            except Exception:  # noqa: BLE001
                _OPS_STATS["collect_errors"] += 1

    def stop(self) -> None:
        self._stop.set()


_SAMPLER: Optional[_Sampler] = None


# ----------------------------------------------------------------------
# multi-window SLO burn-rate alerting
# ----------------------------------------------------------------------
_BURN = {
    "target": _env_float("HEAT_TPU_SLO_TARGET", 0.99, 0.0, 0.999999),
    "fast_s": _env_float("HEAT_TPU_SLO_FAST_S", 60.0, 0.1, 86400.0),
    "slow_s": _env_float("HEAT_TPU_SLO_SLOW_S", 300.0, 0.1, 86400.0),
    "threshold": _env_float("HEAT_TPU_SLO_BURN", 2.0, 0.0, 1e6),
    "min_samples": int(_env_float("HEAT_TPU_SLO_BURN_MIN", 8, 1, 1e6)),
}
_BURN_LOCK = threading.Lock()
#: (metric, tenant) -> {"active", "since", "fired", "fast_burn",
#: "slow_burn", "fast_n", "slow_n"} — tenant "*" is the global row
_ALERTS: "OrderedDict[Tuple[str, str], Dict[str, Any]]" = OrderedDict()
_FINDINGS: deque = deque(maxlen=256)
#: alert rows kept (newest-touched win) — bounded like the tenant labels
_ALERT_CAP = 256
#: burn-edge subscribers (:func:`on_burn`): called as
#: ``callback(metric, tenant, rising, snapshot)`` AFTER ``_BURN_LOCK`` is
#: released — a subscriber may safely read ``burn_report()`` or flip
#: actuators without deadlocking the tick that notified it
_BURN_CALLBACKS: List = []


def on_burn(callback) -> Any:
    """Subscribe ``callback(metric, tenant, rising, snapshot)`` to burn
    alert edges: ``rising=True`` on every ``slo_burn`` firing edge,
    ``False`` on the matching clear. ``snapshot`` is a copy of the alert
    row at the edge. Callbacks run on the ticking thread (the sampler, a
    scrape, or a direct :func:`sample` call) after the burn lock is
    released; one raising subscriber never breaks the tick or the others
    (errors are counted, not propagated). The flight recorder logs every
    dispatch as a ``burn_callback`` event. Returns an unsubscribe
    callable — the autoscaler holds it for its disarm path. Subscriptions
    are configuration: they survive :func:`reset`."""
    if not callable(callback):
        raise TypeError(f"on_burn needs a callable, got {type(callback).__name__}")
    with _BURN_LOCK:
        _BURN_CALLBACKS.append(callback)

    def _unsubscribe() -> None:
        with _BURN_LOCK:
            try:
                _BURN_CALLBACKS.remove(callback)
            except ValueError:  # already unsubscribed: idempotent
                pass

    return _unsubscribe


def _dispatch_burn_edges(edges: List[Tuple[str, str, bool, Dict[str, Any]]]) -> None:
    """Fan each accumulated edge out to the subscribers — called by
    ``_burn_tick`` AFTER ``_BURN_LOCK`` is released, so a callback reading
    ``burn_report()`` (or running a whole autoscale decision) cannot
    deadlock against the tick that produced the edge."""
    if not edges:
        return
    with _BURN_LOCK:
        callbacks = list(_BURN_CALLBACKS)
    if not callbacks:
        return
    for metric, tenant, rising, snapshot in edges:
        for cb in callbacks:
            try:
                cb(metric, tenant, rising, dict(snapshot))
                # the flight ring logs every dispatch (record_event lands
                # on the ring at any active telemetry mode)
                telemetry.record_event(
                    "burn_callback",
                    metric=metric,
                    tenant=tenant,
                    rising=rising,
                    callback=getattr(cb, "__name__", type(cb).__name__),
                )
            except Exception:  # noqa: BLE001 - one subscriber never breaks a tick
                _OPS_STATS["callback_errors"] += 1


def set_burn(
    target: Optional[float] = None,
    fast_s: Optional[float] = None,
    slow_s: Optional[float] = None,
    threshold: Optional[float] = None,
    min_samples: Optional[int] = None,
) -> Dict[str, Any]:
    """Set burn-rate parameters in-process; returns the previous config.
    ``target`` is the SLO objective (0.99 = 1% error budget); an alert
    fires when both windows burn at ``threshold``× the sustainable rate."""
    with _BURN_LOCK:
        prev = dict(_BURN)
        if target is not None:
            if not (0.0 <= float(target) < 1.0):
                raise ValueError(f"target must be in [0, 1), got {target!r}")
            _BURN["target"] = float(target)
        if fast_s is not None:
            _BURN["fast_s"] = max(0.1, float(fast_s))
        if slow_s is not None:
            _BURN["slow_s"] = max(0.1, float(slow_s))
        if threshold is not None:
            _BURN["threshold"] = max(0.0, float(threshold))
        if min_samples is not None:
            _BURN["min_samples"] = max(1, int(min_samples))
    return prev


def _burn_tick(now: Optional[float] = None) -> None:
    """Fold the tenant-tagged SLO sample windows into burn rates and run
    the two-window alert state machine. Rising edges emit ``slo_burn``
    events + findings; falling edges emit ``slo_burn_clear``. Edges are
    accumulated under ``_BURN_LOCK`` and fanned out to :func:`on_burn`
    subscribers only after it is released."""
    now = time.perf_counter() if now is None else now
    edges: List[Tuple[str, str, bool, Dict[str, Any]]] = []
    with _BURN_LOCK:
        fast_s, slow_s = _BURN["fast_s"], _BURN["slow_s"]
        budget = max(1e-9, 1.0 - _BURN["target"])
        threshold, min_n = _BURN["threshold"], _BURN["min_samples"]
        horizon = max(fast_s, slow_s)
        touched = set()
        for metric, dq in health_runtime._SLO_SAMPLES.items():
            limit = health_runtime._SLO_LIMITS.get(metric)
            if limit is None:
                continue
            # one pass over the window: (n, breaches) per tenant per window
            rows: Dict[str, List[int]] = {}
            for item in list(dq):
                ts, v = item[0], item[1]
                tenant = item[2] if len(item) > 2 else None
                age = now - ts
                if age > horizon:
                    continue
                bad = 1 if v > limit else 0
                for t in ("*",) if tenant is None else ("*", str(tenant)):
                    row = rows.setdefault(t, [0, 0, 0, 0])  # fn, fbad, sn, sbad
                    if age <= fast_s:
                        row[0] += 1
                        row[1] += bad
                    if age <= slow_s:
                        row[2] += 1
                        row[3] += bad
            for tenant, (fn, fbad, sn, sbad) in rows.items():
                fast_burn = (fbad / fn / budget) if fn else 0.0
                slow_burn = (sbad / sn / budget) if sn else 0.0
                firing = (
                    fn >= min_n
                    and fast_burn >= threshold
                    and slow_burn >= threshold
                )
                self_key = (metric, tenant)
                touched.add(self_key)
                state = _ALERTS.get(self_key)
                if state is None:
                    if len(_ALERTS) >= _ALERT_CAP:
                        _ALERTS.popitem(last=False)
                    state = _ALERTS[self_key] = {
                        "active": False, "since": None, "fired": 0,
                        "fast_burn": 0.0, "slow_burn": 0.0, "fast_n": 0, "slow_n": 0,
                    }
                else:
                    _ALERTS.move_to_end(self_key)
                state.update(
                    fast_burn=fast_burn, slow_burn=slow_burn, fast_n=fn, slow_n=sn
                )
                _edge(state, metric, tenant, firing, edges)
        # rows that emptied out (no samples left in the slow window) clear
        for key, state in _ALERTS.items():
            if key in touched:
                continue
            state.update(fast_burn=0.0, slow_burn=0.0, fast_n=0, slow_n=0)
            _edge(state, key[0], key[1], False, edges)
    _dispatch_burn_edges(edges)


def _edge(
    state: Dict[str, Any],
    metric: str,
    tenant: str,
    firing: bool,
    edges: List[Tuple[str, str, bool, Dict[str, Any]]],
) -> None:
    """One alert edge under ``_BURN_LOCK``: event + finding on rise, event
    on clear; no-op while the level holds. Each edge is also appended to
    ``edges`` for post-lock subscriber dispatch."""
    if firing and not state["active"]:
        state["active"] = True
        state["since"] = time.time()
        state["fired"] += 1
        finding = {
            "kind": "slo_burn",
            "metric": metric,
            "tenant": tenant,
            "fast_burn": round(state["fast_burn"], 4),
            "slow_burn": round(state["slow_burn"], 4),
            "fast_n": state["fast_n"],
            "threshold": _BURN["threshold"],
            "target": _BURN["target"],
            "ts": state["since"],
        }
        _FINDINGS.append(finding)
        telemetry.record_event(
            "slo_burn", **{k: v for k, v in finding.items() if k not in ("kind", "ts")}
        )
        edges.append((metric, tenant, True, dict(state)))
    elif state["active"] and not firing:
        state["active"] = False
        telemetry.record_event(
            "slo_burn_clear",
            metric=metric,
            tenant=tenant,
            fast_burn=round(state["fast_burn"], 4),
            slow_burn=round(state["slow_burn"], 4),
        )
        edges.append((metric, tenant, False, dict(state)))


def burn_report() -> Dict[str, Any]:
    """Burn-tracker state: config, per-(metric, tenant) alert rows and the
    bounded findings ledger — the JSON the autoscaler and ``/healthz``
    read."""
    with _BURN_LOCK:
        return {
            "config": dict(_BURN),
            "alerts": {
                f"{metric}/{tenant}": dict(state)
                for (metric, tenant), state in _ALERTS.items()
            },
            "findings": list(_FINDINGS),
        }


def burn_findings() -> List[Dict[str, Any]]:
    """Every ``slo_burn`` rising edge this session (bounded, newest last)."""
    with _BURN_LOCK:
        return list(_FINDINGS)


def _burn_alert_active() -> bool:
    with _BURN_LOCK:
        return any(state["active"] for state in _ALERTS.values())


# ----------------------------------------------------------------------
# Prometheus text exposition: render + strict validation
# ----------------------------------------------------------------------
def _escape_label(v: str) -> str:
    return v.replace("\\", "\\\\").replace('"', '\\"').replace("\n", "\\n")


def _escape_help(v: str) -> str:
    return v.replace("\\", "\\\\").replace("\n", "\\n")


def _fmt_value(v: float) -> str:
    if v == math.inf:
        return "+Inf"
    if v == -math.inf:
        return "-Inf"
    if float(v).is_integer() and abs(v) < 1e15:
        return str(int(v))
    return repr(float(v))


def _fmt_labels(labels: Dict[str, str]) -> str:
    if not labels:
        return ""
    inner = ",".join(
        f'{k}="{_escape_label(str(v))}"' for k, v in sorted(labels.items())
    )
    return "{" + inner + "}"


def _render_latency_histogram(lines: List[str]) -> None:
    """The one native-histogram family: cumulative ``le`` buckets straight
    from health_runtime's log-spaced ``_Hist`` rows (the ``*`` overall row
    per metric, global view)."""
    st = health_runtime._H_GLOBAL
    base = health_runtime._HIST_BASE
    for metric in health_runtime._METRICS:
        hist = st.overall[metric]
        if not hist.count:
            continue
        labels = {"metric": metric}
        cum = 0
        for idx in sorted(hist.buckets):
            cum += hist.buckets[idx]
            le = dict(labels, le=_fmt_value(round(base ** (idx + 1), 9)))
            lines.append(f"heat_tpu_latency_seconds_bucket{_fmt_labels(le)} {cum}")
        inf = dict(labels, le="+Inf")
        lines.append(f"heat_tpu_latency_seconds_bucket{_fmt_labels(inf)} {hist.count}")
        lines.append(
            f"heat_tpu_latency_seconds_sum{_fmt_labels(labels)} {_fmt_value(round(hist.total, 9))}"
        )
        lines.append(f"heat_tpu_latency_seconds_count{_fmt_labels(labels)} {hist.count}")


def render(samples: Optional[List[Sample]] = None) -> str:
    """Prometheus text exposition (format 0.0.4) of ``samples`` (default: a
    fresh :func:`collect`): one ``# HELP`` + ``# TYPE`` block per schema'd
    family in schema order, samples sorted by label set, duplicates
    dropped. Unschema'd names are skipped — the registry cannot emit what
    the committed contract does not name."""
    if samples is None:
        samples = collect()
    by_name: Dict[str, Dict[str, float]] = {}
    for name, labels, value in samples:
        if name not in SCHEMA:
            continue
        rendered = _fmt_labels(labels)
        fam = by_name.setdefault(name, {})
        if rendered not in fam:  # first writer wins: no duplicate samples
            fam[rendered] = value
    lines: List[str] = []
    for name, (mtype, help_, _labels) in SCHEMA.items():
        if name == "heat_tpu_latency_seconds":
            head = len(lines)
            lines.append(f"# HELP {name} {_escape_help(help_)}")
            lines.append(f"# TYPE {name} {mtype}")
            body = len(lines)
            _render_latency_histogram(lines)
            if len(lines) == body:  # nothing observed yet: drop the header
                del lines[head:]
            continue
        fam = by_name.get(name)
        if not fam:
            continue
        lines.append(f"# HELP {name} {_escape_help(help_)}")
        lines.append(f"# TYPE {name} {mtype}")
        for rendered in sorted(fam):
            lines.append(f"{name}{rendered} {_fmt_value(fam[rendered])}")
    return "\n".join(lines) + "\n"


def validate_exposition(text: str) -> List[str]:
    """Strict exposition-format check, returning problems (empty = valid):
    every sample belongs to a ``# TYPE``-declared family with a preceding
    ``# HELP``, histogram samples use only the histogram suffixes, values
    parse as floats, label syntax is well-formed, and no (name, labels)
    sample repeats. The test matrix and the ``ops check`` CLI verb run
    this against a live scrape."""
    problems: List[str] = []
    helped: Dict[str, str] = {}
    typed: Dict[str, str] = {}
    seen: set = set()

    def _family(sample_name: str) -> Optional[str]:
        if sample_name in typed:
            return sample_name
        for fam, mtype in typed.items():
            if mtype in (_H, "summary") and sample_name in (
                fam + "_bucket", fam + "_sum", fam + "_count"
            ):
                return fam
        return None

    for lineno, line in enumerate(text.splitlines(), 1):
        if not line.strip():
            continue
        if line.startswith("# HELP "):
            parts = line.split(None, 3)
            if len(parts) < 4:
                problems.append(f"line {lineno}: HELP without text")
                continue
            name = parts[2]
            if name in helped:
                problems.append(f"line {lineno}: duplicate HELP for {name}")
            helped[name] = parts[3]
            continue
        if line.startswith("# TYPE "):
            parts = line.split()
            if len(parts) != 4 or parts[3] not in (_C, _G, _H, "summary", "untyped"):
                problems.append(f"line {lineno}: malformed TYPE line {line!r}")
                continue
            name = parts[2]
            if name in typed:
                problems.append(f"line {lineno}: duplicate TYPE for {name}")
            if name not in helped:
                problems.append(f"line {lineno}: TYPE {name} has no preceding HELP")
            if any(s in seen and s[0] == name for s in seen):  # pragma: no cover
                problems.append(f"line {lineno}: TYPE {name} after its samples")
            typed[name] = parts[3]
            continue
        if line.startswith("#"):
            continue
        # sample line: name{labels} value [timestamp]
        brace = line.find("{")
        if brace >= 0:
            close = line.rfind("}")
            if close < brace:
                problems.append(f"line {lineno}: unbalanced label braces")
                continue
            sample_name = line[:brace]
            label_body = line[brace + 1 : close]
            rest = line[close + 1 :].strip()
            if label_body and not _LABELS_RE.match(label_body):
                problems.append(f"line {lineno}: malformed labels {label_body!r}")
        else:
            fields = line.split()
            sample_name, rest = fields[0], " ".join(fields[1:])
            label_body = ""
        if not _NAME_RE.match(sample_name):
            problems.append(f"line {lineno}: invalid metric name {sample_name!r}")
            continue
        value_field = rest.split()[0] if rest.split() else ""
        try:
            float(value_field.replace("+Inf", "inf").replace("-Inf", "-inf").replace("NaN", "nan"))
        except ValueError:
            problems.append(f"line {lineno}: unparseable value {value_field!r}")
        fam = _family(sample_name)
        if fam is None:
            problems.append(f"line {lineno}: sample {sample_name!r} has no TYPE declaration")
        elif typed[fam] == _H and sample_name == fam:
            problems.append(
                f"line {lineno}: histogram {fam} sample without _bucket/_sum/_count suffix"
            )
        key = (sample_name, label_body)
        if key in seen:
            problems.append(f"line {lineno}: duplicate sample {sample_name}{{{label_body}}}")
        seen.add(key)
    return problems


_NAME_RE = re.compile(r"^[a-zA-Z_:][a-zA-Z0-9_:]*$")
_LABELS_RE = re.compile(
    r'^[a-zA-Z_][a-zA-Z0-9_]*="(?:[^"\\]|\\.)*"'
    r'(?:,[a-zA-Z_][a-zA-Z0-9_]*="(?:[^"\\]|\\.)*")*,?$'
)


# ----------------------------------------------------------------------
# health + readiness checks
# ----------------------------------------------------------------------
def health_status() -> Dict[str, Any]:
    """Liveness: the process is healthy unless the watchdog has tripped
    (a hung collective — restart advised until a ``reset()``) or a burn
    alert is firing. ``{"status": "ok"|"degraded", "checks": {...}}``."""
    wd = health_runtime.watchdog_stats()
    checks = {
        "watchdog": wd["trips"] == 0,
        "slo_burn": not _burn_alert_active(),
    }
    return {
        "status": "ok" if all(checks.values()) else "degraded",
        "checks": checks,
        "watchdog_trips": wd["trips"],
        "last_stall": health_runtime.last_stall(),
    }


def ready_status() -> Dict[str, Any]:
    """Readiness: healthy AND the mesh is up AND global admission is not
    saturated (the global bucket, when armed, projects at least one
    token) AND no peer process is declared lost.
    ``{"status": "ok"|"unready", "checks": {...}}``."""
    doc = health_status()
    checks = dict(doc["checks"])
    checks["mesh"] = _mesh_up()
    admission_ok = True
    try:
        from . import serving

        with serving._LOCK:
            bucket = serving._GLOBAL_BUCKET
        if bucket is not None:
            admission_ok = _bucket_tokens(bucket) >= 1.0
    except Exception:  # pragma: no cover - import-order safety only
        pass
    checks["admission"] = admission_ok
    shedding_ok = True
    try:
        from . import serving

        shedding_ok = not serving._SHED_TIERS
    except Exception:  # pragma: no cover - import-order safety only
        pass
    checks["shedding"] = shedding_ok
    peers_ok = True
    try:
        hook = telemetry._MULTIHOST_HOOK
        if hook is not None:
            # a lost peer means cross-process collectives/barriers cannot
            # complete: unready until the launcher reforms the world
            peers_ok = not (hook().get("peers_lost") or ())
    except Exception:  # pragma: no cover - import-order safety only
        pass
    checks["peers"] = peers_ok
    return {
        "status": "ok" if all(checks.values()) else "unready",
        "checks": checks,
    }


# ----------------------------------------------------------------------
# the ops HTTP server (stdlib ThreadingHTTPServer, daemon threads)
# ----------------------------------------------------------------------
def _debug_report() -> Dict[str, Any]:
    doc = telemetry.report(_state=telemetry._GLOBAL)
    doc.pop("events", None)  # /debug/trace is the timeline's exporter
    doc["burn"] = burn_report()
    return doc


def _debug_trace(analyze: bool) -> Tuple[int, Dict[str, Any]]:
    doc = telemetry.export_trace(path=None)
    if not analyze:
        return 200, doc
    from . import tracelens

    try:
        return 200, tracelens.analyze(doc, allow_partial=True)
    except (tracelens.TraceIncompleteError, ValueError) as exc:
        return 409, {"error": str(exc)}


def _debug_numerics() -> Dict[str, Any]:
    from . import numlens

    return numlens.numerics_block()


def _debug_flight() -> Dict[str, Any]:
    return health_runtime.dump_flight(reason="ops")


#: lazily built handler class — ``http.server`` costs ~50ms of import and
#: a scrape-only client process (the common case) never needs it
_HANDLER_CLS = None


def _handler_cls():
    global _HANDLER_CLS
    if _HANDLER_CLS is not None:
        return _HANDLER_CLS
    from http.server import BaseHTTPRequestHandler
    from urllib.parse import parse_qs, urlparse

    class _OpsHandler(BaseHTTPRequestHandler):
        server_version = "heat-tpu-ops"
        protocol_version = "HTTP/1.1"

        # access logs would interleave with the host process's stdout
        def log_message(self, fmt, *args):  # noqa: D102
            pass

        def _send(self, code: int, body: bytes, ctype: str) -> None:
            self.send_response(code)
            self.send_header("Content-Type", ctype)
            self.send_header("Content-Length", str(len(body)))
            self.end_headers()
            self.wfile.write(body)

        def _send_json(self, code: int, doc: Any) -> None:
            body = json.dumps(
                telemetry._jsonable(doc), indent=2, sort_keys=True, default=str
            ).encode()
            self._send(code, body, "application/json")

        def do_GET(self) -> None:  # noqa: N802 - BaseHTTPRequestHandler API
            url = urlparse(self.path)
            route = url.path.rstrip("/") or "/"
            query = parse_qs(url.query)
            try:
                if route == "/metrics":
                    sample()  # alert state never staler than one scrape
                    self._send(
                        200, render().encode(), "text/plain; version=0.0.4"
                    )
                elif route == "/healthz":
                    doc = health_status()
                    self._send_json(200 if doc["status"] == "ok" else 503, doc)
                elif route == "/readyz":
                    doc = ready_status()
                    self._send_json(200 if doc["status"] == "ok" else 503, doc)
                elif route == "/debug/report":
                    self._send_json(200, _debug_report())
                elif route == "/debug/trace":
                    analyze = query.get("analyze", ["0"])[0] not in (
                        "0", "", "false",
                    )
                    code, doc = _debug_trace(analyze)
                    self._send_json(code, doc)
                elif route == "/debug/flight":
                    self._send_json(200, _debug_flight())
                elif route == "/debug/numerics":
                    self._send_json(200, _debug_numerics())
                elif route == "/debug/burn":
                    self._send_json(200, burn_report())
                else:
                    self._send_json(404, {"error": f"no route {route!r}"})
                    return
                _SCRAPES[route] = _SCRAPES.get(route, 0) + 1
            # a broken debug surface answers 500; never kills the server
            except Exception as exc:  # noqa: BLE001
                _OPS_STATS["scrape_errors"] += 1
                try:
                    self._send_json(
                        500, {"error": f"{type(exc).__name__}: {exc}"}
                    )
                except Exception:  # pragma: no cover - client went away
                    pass

    _HANDLER_CLS = _OpsHandler
    return _OpsHandler


class _OpsServer:
    def __init__(self, host: str, port: int):
        from http.server import ThreadingHTTPServer

        self.httpd = ThreadingHTTPServer((host, port), _handler_cls())
        self.httpd.daemon_threads = True
        self.host, self.port = self.httpd.server_address[:2]
        self._thread = threading.Thread(
            target=self.httpd.serve_forever,
            name="heat-tpu-ops-server",
            daemon=True,
            kwargs={"poll_interval": 0.2},
        )

    def start(self) -> None:
        self._thread.start()

    def stop(self) -> None:
        self.httpd.shutdown()
        self.httpd.server_close()


_SERVER: Optional[_OpsServer] = None
_SERVE_LOCK = threading.Lock()


def serve(port: Optional[int] = None, host: Optional[str] = None) -> int:
    """Arm the ops plane: bind the HTTP server (``port=0`` = ephemeral;
    default ``HEAT_TPU_OPS_PORT``) and start the cadence sampler. Returns
    the bound port. Idempotent: re-arming replaces the previous server."""
    global _SERVER, _SAMPLER
    with _SERVE_LOCK:
        if port is None:
            port = _env_port()
            if port is None:
                raise ValueError(
                    "no port: pass serve(port=...) or set HEAT_TPU_OPS_PORT"
                )
        if host is None:
            host = os.environ.get("HEAT_TPU_OPS_HOST", "127.0.0.1")
        if _SERVER is not None:
            _SERVER.stop()
            _SERVER = None
        if _SAMPLER is None:
            _SAMPLER = _Sampler(_INTERVAL_S)
            _SAMPLER.start()
        _SERVER = _OpsServer(host, int(port))
        _SERVER.start()
        telemetry.record_event("ops_serve", host=_SERVER.host, port=_SERVER.port)
        return _SERVER.port


def shutdown() -> None:
    """Disarm the ops plane: stop the HTTP server and the sampler (the
    registry and alert state survive — they are session data)."""
    global _SERVER, _SAMPLER
    with _SERVE_LOCK:
        if _SERVER is not None:
            _SERVER.stop()
            _SERVER = None
        if _SAMPLER is not None:
            _SAMPLER.stop()
            _SAMPLER = None


def status() -> Dict[str, Any]:
    """Ops-plane state: armed/port/host, sampler cadence, registry + scrape
    counters, burn config and any active alerts."""
    with _SERVE_LOCK:
        armed = _SERVER is not None
        host = _SERVER.host if armed else None
        port = _SERVER.port if armed else None
        sampling = _SAMPLER is not None
    with _SERIES_LOCK:
        live = len(_SERIES)
    with _BURN_LOCK:
        active = [
            {"metric": m, "tenant": t, **{k: v for k, v in s.items()}}
            for (m, t), s in _ALERTS.items()
            if s["active"]
        ]
    return {
        "armed": armed,
        "host": host,
        "port": port,
        "sampling": sampling,
        "interval_s": _INTERVAL_S,
        "series": live,
        "scrapes": dict(_SCRAPES),
        "stats": dict(_OPS_STATS),
        "burn": {"config": dict(_BURN), "active_alerts": active},
    }


def reset() -> None:
    """Clear the session state — series registry, burn alerts + findings,
    scrape/sample counters. Configuration (burn parameters, cadence) and
    an armed server/sampler survive — the ``memledger.reset`` split."""
    with _SERIES_LOCK:
        _SERIES.clear()
    with _BURN_LOCK:
        _ALERTS.clear()
        _FINDINGS.clear()
    _OPS_STATS.update(
        samples=0,
        scrape_errors=0,
        collect_errors=0,
        series_dropped=0,
        sample_ms=0.0,
        callback_errors=0,
    )
    _SCRAPES.clear()


# env arming: HEAT_TPU_OPS_PORT set -> the server comes up with the
# process (warn-and-disarm on a port that will not bind; an import must
# never die because a sidecar already owns the port)
_ENV_PORT = _env_port()
if _ENV_PORT is not None:  # pragma: no cover - exercised via subprocess
    try:
        serve(_ENV_PORT)
    except OSError as exc:
        warnings.warn(
            f"HEAT_TPU_OPS_PORT={_ENV_PORT}: bind failed ({exc}); "
            "the ops server stays disarmed",
            stacklevel=2,
        )

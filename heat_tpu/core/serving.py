"""Multi-tenant serving layer: sessions, persistent program cache, admission.

The north star is heavy traffic from many concurrent short-lived client
computations sharing ONE warm mesh (ROADMAP item 4) — not one long SPMD
script. Everything a service needs around the fused dispatch path already
exists in pieces (scoped telemetry, memledger's headroom gate and hold
semantics, fusion's per-program-key ledger); this module composes them into
a serving surface with three pillars:

**Sessions** (:class:`Session`) — one per client/tenant, entered as a
context manager on the client's thread. A session gets its own telemetry
scope (counters, spans, scoped latency histograms via the
``health_runtime`` seam), its own numeric error policy
(``resilience``' thread-local errstate override), its own numerics-lens
sampling frame, and its own quarantine view (degraded programs and
quarantine hits are billed to the tripping tenant, never a neighbor).
State never bleeds between concurrent client threads: the scope/errstate/
sampling machinery is thread-local, and the global rollup stays intact
underneath.

**Persistent program cache** — ``HEAT_TPU_PROGRAM_CACHE_DIR`` (or
:func:`arm_cache`) wires jax's compilation cache to ``<dir>/xla`` and keeps
an append-only index of fusion's DAG-signature program keys in
``<dir>/programs.jsonl``. A fresh process that forces a previously-seen
signature records a ``disk_hit`` instead of a ``compile`` (the compiled
binary comes off disk), so a warm-started service reaches steady state with
zero recompiles; :func:`warmup` pre-bakes representative chains ahead of
traffic. A malformed dir (unwritable, file-not-dir) warns and disarms at
import — the ``HEAT_TPU_MEMORY_BUDGET`` convention: a typo'd env knob must
not take the process down. Corrupt index lines are skipped with one warning.

**Admission control** — a token-bucket gate on fused dispatches
(``HEAT_TPU_ADMISSION_RATE`` tokens/s, ``HEAT_TPU_ADMISSION_BURST`` bucket
depth), with one global bucket and optionally one per session, fired in
``fusion.force()`` BEFORE the force lock is taken (a tenant sleeping for
refill must block only itself, never convoy neighbours' dispatches behind
the lock) and composed before memledger's headroom gate. A refused chain
stays fully intact — still pending, never degraded, never
double-dispatched — exactly the ``admission_hold`` contract: under the
default ``wait`` policy the force blocks until tokens refill, under
``raise`` (``HEAT_TPU_ADMISSION_POLICY=raise``) an :class:`AdmissionError`
names the session and the bucket that refused.

**Cross-session batching** costs nothing extra: fusion's live-root registry
is global, so small pending roots from different sessions ride one
multi-output dispatch under the same comm/device-set rules. Each root
carries its recording session's name, the dispatch timeline event carries
the ``sessions`` list, and the serving note bills each tenant for its own
roots — shared dispatch, per-tenant attribution.
"""

from __future__ import annotations

import itertools
import json
import os
import threading
import time
import warnings
from collections import OrderedDict, deque
from typing import Any, Dict, List, Optional

from . import fusion, health_runtime, memledger, numlens, resilience, telemetry

__all__ = [
    "AdmissionError",
    "ShedError",
    "Session",
    "arm_cache",
    "cache_stats",
    "sessions_block",
    "session_reports",
    "set_admission",
    "shed",
    "shed_state",
    "warmup",
    "reset",
]


class AdmissionError(RuntimeError):
    """A fused dispatch exceeded the admission token bucket under the
    ``raise`` policy. The message names the session and the bucket
    (``global`` or ``session:<name>``) that refused; the chain it refused
    is untouched — still pending, dispatchable once tokens refill."""


class ShedError(AdmissionError):
    """A fused dispatch from a shed tier was refused by overload
    protection (:func:`shed`, normally flipped by ``ht.autoscale``). Same
    containment contract as every admission refusal: the chain is still
    pending, never degraded, never double-dispatched — it dispatches
    cleanly (or rides a neighbour's batch) once shedding lifts."""


# ----------------------------------------------------------------------
# token buckets
# ----------------------------------------------------------------------
class _TokenBucket:
    """Classic token bucket: ``rate`` tokens/second refill up to ``burst``
    capacity; one fused dispatch costs one token. ``take`` never sleeps —
    it returns the seconds until a token WILL be available so the caller
    owns the wait/raise decision (and the bookkeeping)."""

    __slots__ = ("name", "rate", "burst", "tokens", "ts",
                 "admitted", "refused", "waited_s", "_lock")

    def __init__(self, rate: float, burst: float, name: str):
        self.name = name
        self.rate = float(rate)
        self.burst = max(1.0, float(burst))
        self.tokens = self.burst  # starts full: the first burst is free
        self.ts = time.monotonic()
        self.admitted = 0
        self.refused = 0
        self.waited_s = 0.0
        self._lock = threading.Lock()

    def take(self) -> float:
        """Take one token if available (returns 0.0), else the seconds
        until the bucket refills enough."""
        with self._lock:
            now = time.monotonic()
            self.tokens = min(self.burst, self.tokens + (now - self.ts) * self.rate)
            self.ts = now
            if self.tokens >= 1.0:
                self.tokens -= 1.0
                self.admitted += 1
                return 0.0
            return (1.0 - self.tokens) / self.rate if self.rate > 0 else 60.0

    def give_back(self) -> None:
        """Refund a taken token (a later bucket in the chain refused, or the
        admitted dispatch never ran)."""
        with self._lock:
            self.tokens = min(self.burst, self.tokens + 1.0)
            self.admitted -= 1

    def reconfigure(self, rate: float, burst: float) -> None:
        """Hot-update ``rate``/``burst`` mid-traffic without losing state:
        the ``admitted``/``refused``/``waited_s`` counters survive, and the
        accumulated tokens are first refilled at the OLD rate up to now,
        then clamped to the new burst — a shrink mid-burst takes effect
        immediately instead of granting the old depth one more time."""
        with self._lock:
            now = time.monotonic()
            self.tokens = min(self.burst, self.tokens + (now - self.ts) * self.rate)
            self.ts = now
            self.rate = float(rate)
            self.burst = max(1.0, float(burst))
            self.tokens = min(self.burst, self.tokens)

    def refuse(self) -> None:
        with self._lock:
            self.refused += 1

    def note_wait(self, seconds: float) -> None:
        with self._lock:
            self.waited_s += seconds

    def stats(self) -> Dict[str, Any]:
        return {
            "rate": self.rate,
            "burst": self.burst,
            "admitted": self.admitted,
            "refused": self.refused,
            "waited_s": round(self.waited_s, 6),
        }


# ----------------------------------------------------------------------
# env knobs (warn-and-disarm, the HEAT_TPU_MEMORY_BUDGET convention)
# ----------------------------------------------------------------------
_POLICIES = ("wait", "raise")


def _parse_env_rate(name: str) -> Optional[float]:
    raw = os.environ.get(name)
    if raw is None or not raw.strip():
        return None
    try:
        rate = float(raw)
        if rate <= 0:
            raise ValueError("rate must be > 0")
        return rate
    except (ValueError, TypeError):
        warnings.warn(
            f"{name}={raw!r} is not a positive tokens/second number; the "
            "admission gate stays disarmed",
            stacklevel=1,
        )
        return None


def _parse_env_burst(name: str, default: float) -> float:
    raw = os.environ.get(name)
    if raw is None or not raw.strip():
        return default
    try:
        burst = float(raw)
        if burst < 1:
            raise ValueError("burst must be >= 1")
        return burst
    except (ValueError, TypeError):
        warnings.warn(
            f"{name}={raw!r} is not a bucket depth >= 1; using {default}",
            stacklevel=1,
        )
        return default


def _parse_env_policy() -> str:
    raw = os.environ.get("HEAT_TPU_ADMISSION_POLICY", "wait").strip().lower() or "wait"
    if raw not in _POLICIES:  # a typo'd env knob must not take the process down
        warnings.warn(
            f"HEAT_TPU_ADMISSION_POLICY={raw!r} is not one of {_POLICIES}; "
            "using 'wait'",
            stacklevel=1,
        )
        return "wait"
    return raw


def _parse_env_cache_dir() -> Optional[str]:
    """``HEAT_TPU_PROGRAM_CACHE_DIR``, probed writable. An unwritable path
    or a file-where-a-dir-should-be warns and disarms instead of making
    ``import heat_tpu`` raise."""
    raw = os.environ.get("HEAT_TPU_PROGRAM_CACHE_DIR")
    if raw is None or not raw.strip():
        return None
    path = raw.strip()
    try:
        os.makedirs(path, exist_ok=True)
        probe = os.path.join(path, ".ht_probe")
        with open(probe, "w"):
            pass
        os.remove(probe)
    except OSError as exc:
        warnings.warn(
            f"HEAT_TPU_PROGRAM_CACHE_DIR={raw!r} is not a writable directory "
            f"({exc}); the persistent program cache stays disarmed",
            stacklevel=1,
        )
        return None
    return path


# ----------------------------------------------------------------------
# the persistent program-key index
# ----------------------------------------------------------------------
class _DiskIndex:
    """``programs.jsonl`` under the cache dir: one ``{"key", "family"}``
    line appended per first-compiled program. The index is what lets a
    fresh process distinguish "first compile ever" from "seen before, the
    binary is in jax's on-disk compilation cache" — fusion counts the
    latter as ``disk_hits``, keeping the compile counter an honest retrace
    count across process restarts. Corrupt lines (partial writes, stray
    bytes) are skipped with ONE warning, never a crash."""

    def __init__(self, path: str):
        self.path = path
        self.keys: Dict[str, str] = {}  # key -> family
        self.loaded = 0
        self.skipped = 0
        self._warned = False
        self._lock = threading.Lock()

    def load(self) -> None:
        try:
            with open(self.path, "r") as fh:
                lines = fh.readlines()
        except FileNotFoundError:
            return
        except OSError as exc:
            self._warn_once(f"unreadable ({exc})")
            return
        for line in lines:
            line = line.strip()
            if not line:
                continue
            try:
                rec = json.loads(line)
                key = rec["key"]
                if not isinstance(key, str) or not key:
                    raise ValueError("bad key")
            except (ValueError, KeyError, TypeError):
                self.skipped += 1
                self._warn_once(f"corrupt entry {line[:60]!r}")
                continue
            if key not in self.keys:
                self.keys[key] = str(rec.get("family", "?"))
                self.loaded += 1

    def _warn_once(self, what: str) -> None:
        if not self._warned:
            self._warned = True
            warnings.warn(
                f"persistent program index {self.path}: {what} — skipping "
                "(the cache keeps working; bad entries just recompile)",
                stacklevel=2,
            )

    def has(self, key: str) -> bool:
        return key in self.keys

    def note(self, key: str, family: str) -> None:
        """Record a program key (idempotent; append-only on disk)."""
        with self._lock:
            if key in self.keys:
                return
            self.keys[key] = family
            try:
                with open(self.path, "a") as fh:
                    fh.write(json.dumps({"key": key, "family": family}) + "\n")
            except OSError as exc:
                self._warn_once(f"append failed ({exc})")


# ----------------------------------------------------------------------
# module state
# ----------------------------------------------------------------------
# RLock: Session.__enter__/__exit__ install/uninstall the fusion hooks while
# holding it (so a last-exit teardown cannot race a concurrent first-enter
# and disarm a live session's gates), and the helpers they call take it too
_LOCK = threading.RLock()
_TLS = threading.local()  # per-thread stack of active Sessions
_SESSION_SEQ = itertools.count(1)
#: every session ever entered this telemetry session, active or exited,
#: keyed by name (the archive the CLI `sessions` verb renders)
_SESSIONS: "OrderedDict[str, Session]" = OrderedDict()
_ACTIVE = 0  # entered-and-not-exited count, across all threads

_CACHE_DIR: Optional[str] = None
_INDEX: Optional[_DiskIndex] = None
_XLA_CACHE_WIRED = False
_XLA_PREV_CONFIG = None  # jax cache config to restore on disarm_cache()

_GLOBAL_BUCKET: Optional[_TokenBucket] = None
_POLICY = _parse_env_policy()

#: session tiers: ``interactive`` keeps its tokens under overload;
#: ``batch`` (alias ``preemptible``) is sheddable — the autoscaler flips
#: the shed set and batch-tier dispatches raise :class:`ShedError`
_TIERS = ("interactive", "batch")
_TIER_ALIASES = {"preemptible": "batch"}
#: tiers currently shedding (overload protection active); flipped by
#: :func:`shed` — normally only by the ``ht.autoscale`` controller
_SHED_TIERS: frozenset = frozenset()
#: total ShedErrors raised since reset (the opsplane counter's source)
_SHED_STATS = {"refusals": 0}
_ENV_RATE = _parse_env_rate("HEAT_TPU_ADMISSION_RATE")
_ENV_BURST = _parse_env_burst(
    "HEAT_TPU_ADMISSION_BURST", _ENV_RATE if _ENV_RATE is not None else 1.0
)


def _session_stack() -> List["Session"]:
    stack = getattr(_TLS, "stack", None)
    if stack is None:
        stack = _TLS.stack = []
    return stack


def _current_session() -> Optional["Session"]:
    stack = getattr(_TLS, "stack", None)
    return stack[-1] if stack else None


def _current_session_name() -> Optional[str]:
    stack = getattr(_TLS, "stack", None)
    return stack[-1].name if stack else None


# ----------------------------------------------------------------------
# the fusion seams (set-attribute hooks, installed while sessions exist)
# ----------------------------------------------------------------------
def _bill(names, field: str, per_root: bool = False) -> None:
    """Charge ``field`` once per distinct session in ``names`` (or per root
    when ``per_root``), resolving names through the registry."""
    if not names:
        return
    seen: Dict[str, int] = {}
    for n in names:
        if n is not None:
            seen[n] = seen.get(n, 0) + 1
    with _LOCK:  # reset() deletes exited entries concurrently
        resolved = [(_SESSIONS.get(n), count) for n, count in seen.items()]
    for sess, count in resolved:
        if sess is not None:
            sess.stats[field] += count if per_root else 1


def _on_note(kind: str, **data) -> None:
    """fusion's ``_SERVING_NOTE`` seam: per-session billing + incident
    containment. Called under fusion's force lock; must never raise."""
    try:
        if kind == "dispatch":
            sessions = data.get("sessions")
            _bill(sessions, "dispatches")
            _bill(sessions, "roots", per_root=True)
            trigger = data.get("trigger")
            if data.get("compiled") and trigger is not None:
                with _LOCK:
                    sess = _SESSIONS.get(trigger)
                if sess is not None:
                    sess.stats["compiles"] += 1
            return
        if kind == "degraded":
            sess = _current_session()
            if sess is not None:
                sess.stats["degraded"] += 1
                sess._incident(kind, data)
            return
        if kind == "quarantine_hit":
            names = [n for n in (data.get("sessions") or ()) if n is not None]
            if not names and _current_session() is not None:
                names = [_current_session().name]
            for n in dict.fromkeys(names):
                with _LOCK:
                    sess = _SESSIONS.get(n)
                if sess is not None:
                    sess.stats["quarantine_hits"] += 1
                    sess._incident(kind, data)
            return
        if kind == "mem_refused":
            sess = _current_session()
            if sess is not None:
                sess.stats["mem_refused"] += 1
                sess._incident(kind, data)
    except Exception:  # pragma: no cover - billing never breaks a dispatch
        pass


def _admit(cid) -> Optional[Any]:
    """fusion's ``_ADMIT_HOOK`` seam: the token-bucket gate, composed
    before memledger's headroom gate. fusion calls it in ``force()``
    BEFORE acquiring ``_FORCE_LOCK`` — the ``wait`` policy sleeps until
    refill, and sleeping under the force lock would let one rate-limited
    tenant convoy every other session's dispatches for the full refill
    wait (containment demands the opposite: a tenant tripping its gate
    blocks only itself). The session's own bucket is consulted first
    (cheap containment), then the global one; a raise-refusal refunds the
    session token so the retry is not double-charged. Under ``wait`` the
    force blocks until refill — the chain stays pending the whole time,
    mirroring ``admission_hold``. Returns a refund closure fusion invokes
    when the admitted dispatch never runs (a neighbour's batch landed the
    value during the wait), or ``None`` when no bucket gated.

    Tier shedding composes BEFORE the buckets: a dispatch from a session
    whose tier is in the shed set raises :class:`ShedError` without
    consuming anyone's tokens — interactive traffic keeps the whole
    budget while the overload lasts."""
    sess = _current_session()
    if (sess is not None and _SHED_TIERS and sess.tier in _SHED_TIERS):
        sess.stats["shed"] += 1
        sess._incident("shed", {"tier": sess.tier, "cid": cid})
        _SHED_STATS["refusals"] += 1
        if telemetry._MODE >= 2:
            telemetry.record_event(
                "admission_shed", tier=sess.tier, session=sess.name, cid=cid
            )
        raise ShedError(
            f"dispatch of chain cid={cid} shed: session {sess.name!r} is "
            f"{sess.tier}-tier and the overload controller is shedding "
            f"{sorted(_SHED_TIERS)} — the chain is still pending and "
            "dispatches cleanly once shedding lifts"
        )
    buckets: List[_TokenBucket] = []
    if sess is not None and sess.bucket is not None:
        buckets.append(sess.bucket)
    if _GLOBAL_BUCKET is not None:
        buckets.append(_GLOBAL_BUCKET)
    if not buckets:
        return None
    policy = sess.policy if sess is not None and sess.policy else _POLICY
    taken: List[_TokenBucket] = []
    for bucket in buckets:
        while True:
            wait = bucket.take()
            if wait <= 0.0:
                taken.append(bucket)
                break
            if policy == "raise":
                bucket.refuse()
                for t in taken:  # refund earlier buckets in the chain
                    t.give_back()
                if sess is not None:
                    sess.stats["admission_refused"] += 1
                    sess._incident("admission_refused",
                                   {"bucket": bucket.name, "cid": cid})
                raise AdmissionError(
                    f"dispatch of chain cid={cid} refused by the "
                    f"{bucket.name} admission bucket for session "
                    f"{sess.name if sess is not None else '<none>'} "
                    f"(rate {bucket.rate}/s, burst {int(bucket.burst)}; "
                    f"retry in {wait:.3f}s or use the 'wait' policy) — the "
                    "chain is still pending and dispatches once tokens refill"
                )
            # wait policy: the refused chain stays pending and dispatches
            # when tokens refill (nothing degraded, nothing re-walked).
            # The sleep happens on the CALLING tenant's thread only, with
            # no fusion lock held: neighbours keep dispatching throughout.
            bucket.note_wait(wait)
            if sess is not None:
                sess.stats["admission_waits"] += 1
                sess.stats["admission_waited_s"] += wait
            if telemetry._MODE >= 2:
                telemetry.record_event(
                    "admission_wait", bucket=bucket.name, cid=cid,
                    seconds=round(wait, 6),
                )
            time.sleep(wait)

    def _refund() -> None:
        for t in taken:
            t.give_back()

    return _refund


def _root_priority(session_name: Optional[str]):
    """fusion's ``_ROOT_PRIORITY`` seam: map a root's recording session to
    a deterministic sort key ``(tier_rank, deadline_ms)`` — interactive
    roots (rank 0) batch ahead of unattributed roots (rank 1) ahead of
    batch-tier roots (rank 2), earliest deadline first within a tier. The
    cross-session batch window orders candidates by this key so a
    latency-sensitive root is never convoyed behind (or truncated out of a
    full batch by) a batch tenant's chain. While a tier is being shed, its
    roots return ``fusion._BATCH_EXCLUDED`` instead — a shed chain must
    not free-ride a neighbour's batch while the overload lasts (it stays
    pending and dispatches, or batches, once shedding lifts). Must never
    raise — fusion calls it inside ``_gather_batch`` under the force
    lock."""
    sess = None
    if session_name is not None:
        with _LOCK:
            sess = _SESSIONS.get(session_name)
    if sess is None:
        return (1, float("inf"))
    if _SHED_TIERS and sess.tier in _SHED_TIERS:
        return fusion._BATCH_EXCLUDED
    deadline = sess.deadline_ms if sess.deadline_ms is not None else float("inf")
    return (0 if sess.tier == "interactive" else 2, deadline)


def _install_hooks() -> None:
    fusion._SERVING_NOTE = _on_note
    fusion._SESSION_OF = _current_session_name
    fusion._ROOT_PRIORITY = _root_priority
    _refresh_admit_hook()


def _uninstall_hooks() -> None:
    fusion._SERVING_NOTE = None
    fusion._SESSION_OF = None
    fusion._ROOT_PRIORITY = None
    _refresh_admit_hook()


def _refresh_admit_hook() -> None:
    """The admit hook is live whenever any bucket could gate a dispatch —
    a global env/set_admission bucket, or an active session with its own —
    or a shed set is armed (tier shedding refuses before any bucket)."""
    armed = _GLOBAL_BUCKET is not None or bool(_SHED_TIERS)
    if not armed:
        with _LOCK:
            armed = any(
                s.bucket is not None and s._entered > 0 for s in _SESSIONS.values()
            )
    fusion._ADMIT_HOOK = _admit if armed else None


def shed(tiers) -> frozenset:
    """Flip overload shedding for ``tiers`` (an iterable of tier names;
    empty/``None``/``()`` lifts shedding entirely). While a tier sheds,
    every fused dispatch from a session of that tier raises
    :class:`ShedError` BEFORE any token is taken — interactive traffic
    keeps the whole admission budget. Returns the previous shed set, so
    callers can restore it. Normally driven by ``ht.autoscale``; safe to
    call directly (idempotent, takes effect on the next dispatch)."""
    global _SHED_TIERS
    prev = _SHED_TIERS
    resolved = set()
    for t in tiers or ():
        t = _TIER_ALIASES.get(t, t)
        if t not in _TIERS:
            raise ValueError(
                f"unknown tier {t!r}: tiers are {_TIERS} "
                f"(alias {tuple(_TIER_ALIASES)})"
            )
        resolved.add(t)
    _SHED_TIERS = frozenset(resolved)
    _refresh_admit_hook()
    return prev


def shed_state() -> Dict[str, Any]:
    """The live shed set + refusal counter (pure module state)."""
    return {
        "tiers": sorted(_SHED_TIERS),
        "refusals": _SHED_STATS["refusals"],
    }


#: cross-session micro batch window (seconds). Armed on ``fusion`` whenever
#: >= 2 sessions are concurrently active: each top-level force sleeps this
#: long with the GIL released before dispatching, so the other tenants'
#: threads get to register their pending roots and ride the SAME multi-output
#: program — the thing that keeps N-client steady-state p99 flat instead of
#: convoying N serialized dispatches behind the force lock.
_BATCH_WINDOW = 5e-4


def _refresh_batch_window() -> None:
    fusion._BATCH_WINDOW_S = _BATCH_WINDOW if _ACTIVE >= 2 else 0.0


# ----------------------------------------------------------------------
# Session
# ----------------------------------------------------------------------
class Session:
    """One tenant on the warm mesh, used as a context manager on the
    client's thread::

        with ht.serving.Session("tenant-a", errstate="raise") as sess:
            ...  # every chain recorded here is billed to tenant-a

    Inside the ``with`` block, the calling thread gets: a telemetry scope
    ``session:<name>`` (isolated counters/spans + scoped latency
    histograms), the session's numeric error policy (``errstate`` of
    ``"ignore"``/``"warn"``/``"raise"``; ``None`` inherits the global
    ``ht.errstate``), an isolated numerics-lens sampling frame (``numlens``
    of ``"off"``/``"sample"``/``"full"``; ``None`` inherits the global
    mode but still samples on its own cadence and counters), and — when an
    admission rate is configured — the session's own token bucket composed
    with the global one. Incidents (degraded programs, quarantine hits,
    memory-gate and admission refusals) are recorded on THIS session only:
    a tenant tripping a gate is contained and reported per-session, never
    poisoning neighbors. Thread-safe: distinct threads can run distinct
    sessions concurrently (state is thread-local), and one Session object
    may be entered from several threads at once (each gets its own scope
    entry; the stats roll up)."""

    def __init__(self, name: Optional[str] = None, *,
                 errstate: Optional[str] = None,
                 numlens: Optional[str] = None,
                 admission_rate: Optional[float] = None,
                 admission_burst: Optional[float] = None,
                 policy: Optional[str] = None,
                 tier: Optional[str] = None,
                 deadline_ms: Optional[float] = None):
        self.name = name if name else f"session{next(_SESSION_SEQ)}"
        if errstate is not None and errstate not in ("ignore", "warn", "raise"):
            raise ValueError(
                f"errstate must be one of ('ignore', 'warn', 'raise'), got {errstate!r}"
            )
        if policy is not None and policy not in _POLICIES:
            raise ValueError(f"policy must be one of {_POLICIES}, got {policy!r}")
        tier = _TIER_ALIASES.get(tier, tier)
        if tier is not None and tier not in _TIERS:
            raise ValueError(
                f"tier must be one of {_TIERS} (alias {tuple(_TIER_ALIASES)}), "
                f"got {tier!r}"
            )
        self.tier = tier or "interactive"
        if deadline_ms is not None and not float(deadline_ms) > 0:
            raise ValueError(f"deadline_ms must be > 0, got {deadline_ms!r}")
        self.deadline_ms = None if deadline_ms is None else float(deadline_ms)
        self._errstate = errstate
        self._numlens = numlens
        self.policy = policy
        rate = admission_rate if admission_rate is not None else _ENV_RATE
        if rate is not None:
            burst = admission_burst if admission_burst is not None else \
                max(_ENV_BURST, 1.0)
            self.bucket: Optional[_TokenBucket] = _TokenBucket(
                rate, burst, f"session:{self.name}"
            )
        else:
            self.bucket = None
        self.stats: Dict[str, Any] = {
            "dispatches": 0,
            "roots": 0,
            "compiles": 0,
            "degraded": 0,
            "quarantine_hits": 0,
            "mem_refused": 0,
            "admission_refused": 0,
            "admission_waits": 0,
            "admission_waited_s": 0.0,
            "shed": 0,
        }
        self.incidents: deque = deque(maxlen=64)
        self._entered = 0  # concurrent __enter__ count, across threads
        self._sess_tls = threading.local()  # per-thread enter bookkeeping

    # -- lifecycle ------------------------------------------------------
    def __enter__(self) -> "Session":
        global _ACTIVE
        with _LOCK:
            registered = _SESSIONS.get(self.name)
            if (registered is not None and registered is not self
                    and registered._entered > 0):
                raise ValueError(
                    f"a Session named {self.name!r} is already ACTIVE (names "
                    "are the billing key — two live tenants must not share "
                    "one); an exited session's name is reusable"
                )
            _SESSIONS[self.name] = self  # reusing a name rolls the archive over
            self._entered += 1
            _ACTIVE += 1
            # install while still holding _LOCK: a concurrent last-exit in
            # another thread must not observe _ACTIVE drop to 0, release,
            # and then tear the hooks down AFTER we installed them
            if fusion._SERVING_NOTE is None:
                _install_hooks()
            elif self.bucket is not None:
                _refresh_admit_hook()
            _refresh_batch_window()
        frames = getattr(self._sess_tls, "frames", None)
        if frames is None:
            frames = self._sess_tls.frames = []
        scope_cm = telemetry.scope(f"session:{self.name}")
        scope_cm.__enter__()
        if self._errstate is not None:
            resilience._push_errstate(
                None if self._errstate == "ignore" else self._errstate
            )
        numlens._push_session(self._numlens)
        _session_stack().append(self)
        frames.append(scope_cm)
        return self

    def __exit__(self, *exc) -> None:
        global _ACTIVE
        stack = _session_stack()
        for i in range(len(stack) - 1, -1, -1):
            if stack[i] is self:
                del stack[i]
                break
        numlens._pop_session()
        if self._errstate is not None:
            resilience._pop_errstate()
        frames = getattr(self._sess_tls, "frames", None)
        if frames:
            frames.pop().__exit__(*exc)
        with _LOCK:
            self._entered -= 1
            _ACTIVE -= 1
            # teardown under the SAME lock as the check: deciding last=True,
            # releasing, and uninstalling later would race a concurrent
            # __enter__ (0→1 + install in the window) and silently disarm
            # the new session's admission/billing/containment hooks
            if _ACTIVE == 0:
                _uninstall_hooks()
            elif self.bucket is not None:
                _refresh_admit_hook()
            _refresh_batch_window()

    # -- reporting ------------------------------------------------------
    def _incident(self, kind: str, data: Dict[str, Any]) -> None:
        rec = {"kind": kind}
        rec.update({k: v for k, v in data.items() if k != "sessions"})
        self.incidents.append(rec)

    def quarantined_programs(self) -> List[str]:
        """Program keys THIS session saw degrade or hit quarantine — the
        per-session quarantine view (the global ledger is in
        ``fusion.cache_stats()``)."""
        keys = []
        for rec in self.incidents:
            if rec["kind"] in ("degraded", "quarantine_hit"):
                key = rec.get("program")
                if key and key not in keys:
                    keys.append(key)
        return keys

    def report(self) -> Dict[str, Any]:
        """This session's block: billing counters, incidents, quarantine
        view and bucket stats. Pure module state — never forces, never
        initializes a backend."""
        doc: Dict[str, Any] = {
            "name": self.name,
            "active": self._entered > 0,
            "tier": self.tier,
            "deadline_ms": self.deadline_ms,
            "errstate": self._errstate or "inherit",
            "numlens": self._numlens or "inherit",
            "stats": dict(self.stats),
            "incidents": list(self.incidents),
            "quarantine": self.quarantined_programs(),
        }
        if self.bucket is not None:
            doc["bucket"] = self.bucket.stats()
        return doc


# ----------------------------------------------------------------------
# the persistent cache: arming + warmup
# ----------------------------------------------------------------------
def arm_cache(path: str) -> Dict[str, Any]:
    """Arm the persistent program cache at ``path`` (the programmatic form
    of ``HEAT_TPU_PROGRAM_CACHE_DIR``): wire jax's compilation cache to
    ``<path>/xla`` (best-effort — accounting works even where the backend
    does not persist binaries) and load the program-key index from
    ``<path>/programs.jsonl``. Returns ``{"dir", "index_keys", "skipped"}``."""
    global _CACHE_DIR, _INDEX, _XLA_CACHE_WIRED, _XLA_PREV_CONFIG
    os.makedirs(path, exist_ok=True)
    if not _XLA_CACHE_WIRED:
        try:
            import jax

            _XLA_PREV_CONFIG = (
                jax.config.jax_compilation_cache_dir,
                jax.config.jax_persistent_cache_min_compile_time_secs,
                jax.config.jax_persistent_cache_min_entry_size_bytes,
            )
            jax.config.update("jax_compilation_cache_dir",
                              os.path.join(path, "xla"))
            # tiny serving programs must cache too: drop the default
            # minimum-compile-time and minimum-entry-size thresholds
            jax.config.update("jax_persistent_cache_min_compile_time_secs", 0)
            jax.config.update("jax_persistent_cache_min_entry_size_bytes", -1)
            _XLA_CACHE_WIRED = True
        except Exception as exc:  # pragma: no cover - backend-dependent
            warnings.warn(
                f"could not wire jax's compilation cache ({exc!r}); the "
                "program-key index still arms (disk hits are counted, the "
                "backend just recompiles)",
                stacklevel=2,
            )
    _CACHE_DIR = path
    _INDEX = _DiskIndex(os.path.join(path, "programs.jsonl"))
    _INDEX.load()
    fusion._DISK_INDEX = _INDEX
    return {"dir": path, "index_keys": len(_INDEX.keys), "skipped": _INDEX.skipped}


def disarm_cache() -> None:
    """Detach the persistent index and restore jax's compilation-cache
    config — leaving it pointed at a caller-owned (possibly deleted) dir
    would make every later compile warn about failed cache writes."""
    global _CACHE_DIR, _INDEX, _XLA_CACHE_WIRED, _XLA_PREV_CONFIG
    _CACHE_DIR = None
    _INDEX = None
    fusion._DISK_INDEX = None
    if _XLA_CACHE_WIRED and _XLA_PREV_CONFIG is not None:
        try:
            import jax

            jax.config.update("jax_compilation_cache_dir", _XLA_PREV_CONFIG[0])
            jax.config.update(
                "jax_persistent_cache_min_compile_time_secs", _XLA_PREV_CONFIG[1]
            )
            jax.config.update(
                "jax_persistent_cache_min_entry_size_bytes", _XLA_PREV_CONFIG[2]
            )
        except Exception:  # pragma: no cover - backend-dependent
            pass
        _XLA_CACHE_WIRED = False
        _XLA_PREV_CONFIG = None


def warmup(signatures) -> Dict[str, int]:
    """Pre-bake the program cache ahead of traffic. Each item is either a
    zero-arg callable recording one representative chain (its result is
    forced — compiling, or disk-loading when the signature was seen by an
    earlier process) or a bare program-key string to seed the persistent
    index directly. Returns how the warming went::

        {"warmed": n, "compiles": Δ, "disk_hits": Δ, "seeded": k}
    """
    before = fusion.cache_stats()
    warmed = seeded = 0
    for item in signatures:
        if isinstance(item, str):
            if _INDEX is not None:
                _INDEX.note(item, "?")
                seeded += 1
            continue
        result = item()
        for out in result if isinstance(result, (tuple, list)) else (result,):
            payload = getattr(out, "_payload", out)
            forced = fusion.force(payload)
            ready = getattr(forced, "block_until_ready", None)
            if ready is not None:
                ready()
        warmed += 1
    after = fusion.cache_stats()
    return {
        "warmed": warmed,
        "seeded": seeded,
        "compiles": after["compiles"] - before["compiles"],
        "disk_hits": after["disk_hits"] - before["disk_hits"],
    }


def cache_stats() -> Dict[str, Any]:
    """``fusion.cache_stats()`` plus the persistent layer: where the cache
    dir is (or None disarmed), how many keys the index holds, and how many
    corrupt lines were skipped loading it."""
    st = fusion.cache_stats()
    st["persistent_dir"] = _CACHE_DIR
    st["index_keys"] = 0 if _INDEX is None else len(_INDEX.keys)
    st["index_skipped"] = 0 if _INDEX is None else _INDEX.skipped
    return st


# ----------------------------------------------------------------------
# admission configuration
# ----------------------------------------------------------------------
def set_admission(rate: Optional[float], burst: Optional[float] = None,
                  policy: Optional[str] = None) -> None:
    """Arm (or, with ``rate=None``, disarm) the GLOBAL admission bucket —
    the programmatic form of ``HEAT_TPU_ADMISSION_RATE``/``_BURST``/
    ``_POLICY``. Per-session buckets are per-:class:`Session` kwargs.

    Changing rate/burst on an already-armed bucket reconfigures it IN
    PLACE: the ``refused``/``waited_s``/``admitted`` counters and the
    accumulated tokens survive (tokens clamp to the new burst), so a
    mid-traffic retune — the autoscaler's bread and butter — never zeroes
    the ops plane's admission counters."""
    global _GLOBAL_BUCKET, _POLICY
    if policy is not None:
        if policy not in _POLICIES:
            raise ValueError(f"policy must be one of {_POLICIES}, got {policy!r}")
        _POLICY = policy
    if rate is None:
        _GLOBAL_BUCKET = None
    else:
        if rate <= 0:
            raise ValueError(f"rate must be > 0 tokens/second, got {rate}")
        resolved_burst = burst if burst is not None else max(rate, 1.0)
        if _GLOBAL_BUCKET is not None:
            _GLOBAL_BUCKET.reconfigure(rate, resolved_burst)
        else:
            _GLOBAL_BUCKET = _TokenBucket(rate, resolved_burst, "global")
    _refresh_admit_hook()


# ----------------------------------------------------------------------
# report surfaces
# ----------------------------------------------------------------------
def session_reports() -> List[Dict[str, Any]]:
    """Every session's report block (active and exited), entry order."""
    with _LOCK:
        sessions = list(_SESSIONS.values())
    return [s.report() for s in sessions]


def sessions_block() -> Dict[str, Any]:
    """The ``report()["serving"]`` payload: per-session blocks, the global
    admission bucket, and the persistent-cache summary. Pure module state —
    never forces, never initializes a backend."""
    with _LOCK:
        sessions = list(_SESSIONS.values())
    return {
        "sessions": [s.report() for s in sessions],
        "active": sum(1 for s in sessions if s._entered > 0),
        "admission": {
            "policy": _POLICY,
            "global": None if _GLOBAL_BUCKET is None else _GLOBAL_BUCKET.stats(),
            "shed_tiers": sorted(_SHED_TIERS),
            "shed_refusals": _SHED_STATS["refusals"],
        },
        "cache": {
            "persistent_dir": _CACHE_DIR,
            "index_keys": 0 if _INDEX is None else len(_INDEX.keys),
            "disk_hits": fusion._STATS["disk_hits"],
        },
    }


def reset() -> None:
    """Forget exited sessions and zero the global bucket's counters (active
    sessions and the arming itself — cache dir, rates — are configuration
    and survive, mirroring ``memledger.reset``). Called from
    ``telemetry.reset()`` so the joined report surfaces clear together."""
    with _LOCK:
        for name in [n for n, s in _SESSIONS.items() if s._entered == 0]:
            del _SESSIONS[name]
        _refresh_batch_window()
    if _GLOBAL_BUCKET is not None:
        with _GLOBAL_BUCKET._lock:
            _GLOBAL_BUCKET.admitted = 0
            _GLOBAL_BUCKET.refused = 0
            _GLOBAL_BUCKET.waited_s = 0.0
    _SHED_STATS["refusals"] = 0


# ----------------------------------------------------------------------
# import-time arming from the env knobs
# ----------------------------------------------------------------------
_env_cache_dir = _parse_env_cache_dir()
if _env_cache_dir is not None:
    arm_cache(_env_cache_dir)
if _ENV_RATE is not None:
    _GLOBAL_BUCKET = _TokenBucket(_ENV_RATE, _ENV_BURST, "global")
    _refresh_admit_hook()

# per-session label export (set-attribute, like the fusion seams): SLO
# latency samples carry the recording thread's session name, so the ops
# plane's burn-rate windows can group per tenant without health_runtime
# importing the serving layer
health_runtime._TENANT_HOOK = _current_session_name

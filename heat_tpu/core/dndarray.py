"""DNDarray — the distributed n-dimensional array.

TPU-native re-design of reference heat/core/dndarray.py. The reference pairs a
*local* ``torch.Tensor`` per MPI rank with global metadata
(dndarray.py:63-87) and hand-codes every global<->local translation
(getitem :652-908, resplit_ :1235-1357, redistribute_ :1029-1233, halos
:360-441). Here the payload is a single *global* ``jax.Array`` carrying a
``NamedSharding`` over the device mesh: global indexing, resharding and
collective insertion are XLA/GSPMD's job, so the thousand lines of index
translation disappear while the user-facing model — ``gshape`` + one ``split``
axis — stays identical.

Key semantic notes
------------------
* ``larray`` returns the **global logical** ``jax.Array`` (the natural JAX
  handle for local compute under SPMD). Per-device shards are exposed via
  ``lshards``/``lshape``/``lshape_map``.
* Arrays are always *balanced* in GSPMD's ceil-division layout; the
  reference's ragged ``lshape_map``/``balanced=False`` machinery
  (dndarray.py:57-60) intentionally does not exist (SURVEY.md §7 design
  stance). Global sizes not divisible by the mesh size are handled by
  **pad+mask**: the stored *physical* payload (``parray``) is zero-padded
  along the split axis to ``p * ceil(n/p)`` — a suffix of the global dim —
  so every device holds exactly one block-sized shard; ``gshape`` stays
  logical and ``larray`` slices the padding off. The reference instead
  carries ragged local chunks per rank (dndarray.py:57-60).
* "In-place" methods (``resplit_``, ``balance_``, ``__setitem__``) mutate the
  wrapper's handle to a new immutable ``jax.Array`` — aliasing differs from
  the reference (documented deviation).
* Under the eager fusion engine (``core/fusion.py``) the payload may
  transiently be a recorded-but-undispatched ``fusion.LazyArray`` expression
  chain; ``parray``/``larray`` are the forcing points that materialize it as
  one cached jitted program. No public API ever returns unmaterialized state.
"""

from __future__ import annotations

import functools
import warnings
from typing import Iterable, List, Optional, Sequence, Tuple, Union

import jax
import jax.numpy as jnp
import numpy as np

from . import communication as comm_module
from . import devices, fusion, health_runtime, memledger, resilience, telemetry, types
from .communication import Communication, MeshCommunication
from .stride_tricks import sanitize_axis

__all__ = ["DNDarray"]

# forcing-point attribution scopes (telemetry): pushed only when a recorded
# chain is actually pending, so the non-lazy hot paths pay one isinstance
_T_LARRAY = telemetry.force_trigger("larray")
_T_INDEXING = telemetry.force_trigger("indexing")
_T_PYTREE = telemetry.force_trigger("pytree")
_T_COLLECTIVE = telemetry.force_trigger("collective")

Scalar = Union[int, float, bool, complex]


class LocalIndex:
    """Marker wrapper to index into the local shard (reference dndarray.py:34-48).

    Under the global-view runtime, indexing ``x.lloc[key]`` addresses the
    first addressable shard; provided for API parity.
    """

    def __init__(self, obj, key=None):
        self.obj = obj
        self.key = key

    def __getitem__(self, key):
        return self.obj[key]

    def __setitem__(self, key, value):
        self.obj[key] = value


class DNDarray:
    """Distributed N-Dimensional array backed by a sharded global ``jax.Array``.

    Parameters
    ----------
    array : jax.Array
        Global payload (already placed under the intended sharding).
    gshape : tuple of int
        Global shape (must equal ``array.shape``).
    dtype : heat_tpu.core.types.datatype
        Element type class.
    split : int or None
        The single distribution axis, or None for replicated.
    device : heat_tpu.core.devices.Device
    comm : MeshCommunication
    balanced : bool
        Always True in this runtime; kept for API parity.
    """

    def __init__(
        self,
        array: jax.Array,
        gshape: Tuple[int, ...],
        dtype,
        split: Optional[int],
        device,
        comm: Communication,
        balanced: bool = True,
    ):
        self.__gshape = tuple(int(s) for s in gshape)
        self.__dtype = dtype
        self.__split = split
        self.__device = device
        self.__comm = comm
        self.__balanced = True
        # pad+mask for ragged splits: if the (logical) payload's split dim is
        # not divisible by the mesh size, physically pad it to p*ceil(n/p) and
        # shard — every device then holds one block-sized shard instead of a
        # full replica (reference carries ragged chunks per rank,
        # dndarray.py:57-60; SURVEY.md §7 prescribes pad+mask on TPU).
        # Payloads arriving already at the padded physical shape (internal
        # reconstructions, e.g. astype) are stored as-is.
        if (
            split is not None
            and isinstance(array, jax.Array)
            and array.ndim > 0
            and split < array.ndim
            and tuple(array.shape) == self.__gshape
            and comm is not None
            and self.__gshape[split] % comm.size != 0
        ):
            array = _pad_and_place(array, split, comm)
        self.__array = array
        if isinstance(array, jax.Array):
            # live-buffer ledger attribution (core/memledger.py): wrapper
            # payloads are the "dndarray" owner class
            memledger.tag(array, "dndarray")

    # ------------------------------------------------------------------
    # basic properties
    # ------------------------------------------------------------------
    @property
    def balanced(self) -> bool:
        """Arrays are always balanced under GSPMD (reference dndarray.py:160)."""
        return True

    @property
    def comm(self) -> MeshCommunication:
        return self.__comm

    @property
    def device(self):
        return self.__device

    @property
    def dtype(self):
        return self.__dtype

    @property
    def gshape(self) -> Tuple[int, ...]:
        return self.__gshape

    @property
    def shape(self) -> Tuple[int, ...]:
        return self.__gshape

    @property
    def ndim(self) -> int:
        return len(self.__gshape)

    @property
    def size(self) -> int:
        return int(np.prod(self.__gshape, dtype=np.int64)) if self.__gshape else 1

    gnumel = size

    @property
    def lnumel(self) -> int:
        return int(np.prod(self.lshape, dtype=np.int64))

    @property
    def nbytes(self) -> int:
        return self.size * np.dtype(self.__dtype.jax_type()).itemsize

    gnbytes = nbytes

    @property
    def lnbytes(self) -> int:
        return self.lnumel * np.dtype(self.__dtype.jax_type()).itemsize

    @property
    def padded(self) -> bool:
        """True when the physical payload carries suffix padding along the
        split axis (ragged global size, see module docstring)."""
        s = self.__split
        return s is not None and s < self.__array.ndim and (
            int(self.__array.shape[s]) != self.__gshape[s]
        )

    @property
    def _payload(self):
        """Internal: the raw stored payload WITHOUT forcing — a ``jax.Array``
        or, while a recorded op chain is pending, a ``fusion.LazyArray``.
        Only the fusion recorder should consume this; everything else goes
        through :attr:`parray`/:attr:`larray`, which force."""
        return self.__array

    @property
    def parray(self) -> jax.Array:
        """The *physical* payload: the stored ``jax.Array``, zero-padded along
        the split axis to ``p * ceil(n/p)`` when the global size is ragged.
        Pad-aware fast paths (elementwise engines, shard_map kernels) may
        compute on it directly; the padding region's content is unspecified.

        FORCING POINT: a pending recorded op chain (``fusion.LazyArray``
        payload) is materialized here as one cached jitted program and the
        result is placed under the split sharding; every payload consumer
        (``larray``, ``numpy()``, indexing, printing, I/O, collectives,
        linalg, the eager engine fallbacks) funnels through this property."""
        arr = self.__array
        if isinstance(arr, fusion.LazyArray):
            lazy = arr
            arr = fusion.force(arr)
            if isinstance(arr, jax.core.Tracer):
                # forced inside an enclosing trace: the value belongs to that
                # trace — hand it over but never store it on the wrapper
                return arr
            split = self.__split
            if split is not None and (arr.ndim == 0 or split >= arr.ndim):
                split = None
            if resilience._ERRSTATE is not None or resilience._TLS_ARMED:
                # numeric error policy at the forcing seam, on the LOGICAL
                # extent only: the padding suffix of a ragged split holds
                # unspecified garbage (log(0) = -inf) and must not be
                # checked. A raise leaves the wrapper unforced (the cached
                # program makes re-forcing under "ignore" cheap).
                check_val = arr
                if split is not None and int(arr.shape[split]) != self.__gshape[split]:
                    idx = [slice(None)] * arr.ndim
                    idx[split] = slice(0, self.__gshape[split])
                    check_val = arr[tuple(idx)]
                # provenance: the fused program key stamped on the root at
                # force time + the chain's correlation id — a nonfinite
                # finding names its producer, not just the catch point
                resilience.check_nonfinite(
                    check_val, "force",
                    program=getattr(lazy, "program", None), cid=lazy.cid,
                )
            arr = _ensure_split(arr, split, self.__comm)
            self.__array = arr
            # re-attribute the forced value: the async future ("fusion")
            # has been claimed by this wrapper
            memledger.tag(arr, "dndarray")
        return arr

    def _force_payload(self, scope) -> jax.Array:
        """:attr:`parray` with the forcing point attributed to ``scope`` when
        a recorded chain is pending (telemetry forcing-point attribution; the
        outermost scope wins, so e.g. print-over-larray reads as print)."""
        if isinstance(self.__array, fusion.LazyArray):
            with scope:
                return self.parray
        return self.parray

    def _note_blocking_sync(self, kind: str):
        """Telemetry seam for host boundaries (``item``/``numpy``/shard
        reads): counted as a *blocking sync* only when a pending recorded
        chain must be materialized synchronously here — reading a value whose
        program is already dispatched (async forcing) is free and does not
        count. One isinstance on the disabled path.

        Carries the pending chain's correlation id into the trace timeline
        and returns the (verbose-mode) timeline event so the call site can
        close it via ``telemetry.end_blocking_sync`` once the host holds the
        value — the exported trace then shows the sync's true wall duration."""
        if telemetry._MODE:
            arr = self.__array
            if isinstance(arr, fusion.LazyArray) and arr._value is None:
                return telemetry.record_blocking_sync(kind, cid=arr.cid)
        return None

    @property
    def larray(self) -> jax.Array:
        """The **logical** global ``jax.Array`` (see module docstring): the
        physical payload with any split-axis suffix padding sliced off.
        Forces a pending recorded chain (see :attr:`parray`)."""
        arr = self._force_payload(_T_LARRAY)
        if not self.padded:
            return arr
        idx = [slice(None)] * arr.ndim
        idx[self.__split] = slice(0, self.__gshape[self.__split])
        return arr[tuple(idx)]

    @larray.setter
    def larray(self, array: jax.Array):
        """Replace the payload with a new **logical** array (reference
        dndarray.py:229-247); shape/dtype metadata is re-derived and ragged
        splits are re-padded."""
        if not isinstance(array, jax.Array):
            raise TypeError(f"larray must be a jax.Array, got {type(array)}")
        self.__gshape = tuple(int(s) for s in array.shape)
        self.__dtype = types.canonical_heat_type(array.dtype)
        split = self.__split
        if split is not None and (array.ndim == 0 or split >= array.ndim):
            self.__split = split = None
        if split is not None and self.__gshape[split] % self.__comm.size != 0:
            array = _pad_and_place(array, split, self.__comm)
        self.__array = array
        memledger.tag(array, "dndarray")

    def _replace(
        self, array: jax.Array, split: Optional[int], gshape: Optional[Tuple[int, ...]] = None
    ) -> "DNDarray":
        """Internal: swap payload AND split metadata consistently (used by the
        op engines' ``out=`` paths). With ``gshape`` given, ``array`` is taken
        as the physical (possibly padded) payload for that logical shape."""
        self.__split = split
        if gshape is not None:
            gshape = tuple(int(s) for s in gshape)
            expected = list(gshape)
            if split is not None and split < len(expected):
                p = self.__comm.size
                n = expected[split]
                expected[split] = (-(-n // p) if n else 0) * p
            if tuple(array.shape) not in (gshape, tuple(expected)):
                raise ValueError(
                    f"physical payload shape {tuple(array.shape)} matches neither the "
                    f"logical shape {gshape} nor its padded form {tuple(expected)}"
                )
            self.__array = array
            self.__gshape = gshape
            self.__dtype = types.canonical_heat_type(array.dtype)
            memledger.tag(array, "dndarray")
        else:
            self.larray = array
        return self

    def _adopt(self, other: "DNDarray") -> "DNDarray":
        """Internal ``out=`` seam, the deferred form of ``_replace``: take
        ``other``'s payload and metadata WITHOUT forcing — a pending recorded
        chain stays pending and this wrapper becomes its async-forcing root.
        Concrete payloads route through ``_replace`` (identical semantics)."""
        payload = other._payload
        if isinstance(payload, fusion.LazyArray) and payload._value is None:
            self.__gshape = other.gshape
            self.__dtype = other.dtype
            self.__split = other.split
            self.__array = payload
            fusion.register_root(self)
            return self
        return self._replace(other.parray, other.split, gshape=other.gshape)

    @property
    def lshards(self) -> List[np.ndarray]:
        """Per-device **logical** local shards (host copies), in device order:
        each physical shard with its padding rows sliced off (tail devices of
        a ragged split may hold empty logical shards)."""
        self._note_blocking_sync("shards")
        phys = self.parray
        if not self.padded:
            return [np.asarray(s.data) for s in phys.addressable_shards]
        split = self.__split
        counts, _ = self.__comm.counts_displs_shape(self.__gshape, split)
        block = int(phys.shape[split]) // self.__comm.size
        out = []
        for s in phys.addressable_shards:
            start = s.index[split].start or 0
            rank = start // block if block else 0
            idx = [slice(None)] * self.__array.ndim
            idx[split] = slice(0, counts[rank])
            out.append(np.asarray(s.data[tuple(idx)]))
        return out

    def ranked_shards(self):
        """Yield ``(rank, block)`` for every shard THIS process addresses, in
        mesh-rank order; each block is the shard's **logical** extent as a
        host numpy array (physical split-axis padding trimmed — pad+mask
        contract). Ragged-tail ranks whose logical count is zero are skipped;
        a replicated / 0-d array yields the single pair ``(0, full array)``.

        This is the shard/stream protocol shared by the streaming file
        writers (``core/io.py`` — HDF5 hyperslabs, CSV rows, npy buffers) and
        the sharded checkpoint writer (``utils/checkpoint.py``): one host
        transfer per block, never a global gather. Forces a pending recorded
        chain (see :attr:`parray`)."""
        self._note_blocking_sync("shards")
        split = self.__split
        if split is None or self.ndim == 0:
            yield 0, np.asarray(self.larray)  # local payload, not a gather
            return
        counts, _ = self.__comm.counts_displs_shape(self.__gshape, split)
        phys = self.parray
        block = int(phys.shape[split]) // self.__comm.size
        shards = sorted(phys.addressable_shards, key=lambda s: s.index[split].start or 0)
        for s in shards:
            r = (s.index[split].start or 0) // block if block else 0
            c = counts[r]
            if c:
                idx = [slice(None)] * self.ndim
                idx[split] = slice(0, c)
                yield r, np.asarray(s.data[tuple(idx)])

    @property
    def lshape(self) -> Tuple[int, ...]:
        """Logical shape of this process's representative device shard
        (reference dndarray.py:301 reports the calling rank's local tensor;
        the analog under one controller per host is the first rank THIS
        process addresses — multihost.representative_rank — so every host
        reports a shard it actually holds; contract in
        doc/internals_distribution.md)."""
        from .multihost import representative_rank

        rank = representative_rank(self.__comm.devices)
        _, lshape, _ = self.__comm.chunk(self.__gshape, self.__split, rank=rank)
        return lshape

    @property
    def lshape_map(self):
        """(n_devices, ndim) map of shard shapes (reference dndarray.py:569-600:
        collective metadata exchange; here deterministic arithmetic)."""
        from . import factories

        lmap = self.__comm.lshape_map(self.__gshape, self.__split)
        return factories.array(lmap, dtype=types.int64, device=self.__device, comm=self.__comm)

    @property
    def split(self) -> Optional[int]:
        return self.__split

    @property
    def stride(self) -> Tuple[int, ...]:
        """Strides in elements, C-order (reference dndarray.py:321)."""
        strides = []
        acc = 1
        for s in reversed(self.__gshape):
            strides.append(acc)
            acc *= int(s)
        return tuple(reversed(strides))

    @property
    def strides(self) -> Tuple[int, ...]:
        """Strides in bytes (reference dndarray.py:330)."""
        item = np.dtype(self.__dtype.jax_type()).itemsize
        return tuple(s * item for s in self.stride)

    @property
    def T(self) -> "DNDarray":
        from .linalg import basics

        return basics.transpose(self, None)

    @property
    def real(self) -> "DNDarray":
        from . import complex_math

        return complex_math.real(self)

    @property
    def imag(self) -> "DNDarray":
        from . import complex_math

        return complex_math.imag(self)

    @property
    def lloc(self) -> LocalIndex:
        return LocalIndex(self)

    # ------------------------------------------------------------------
    # distribution management
    # ------------------------------------------------------------------
    def is_distributed(self) -> bool:
        """True if data lives on more than one device (reference dndarray.py:957)."""
        return self.__split is not None and self.__comm.is_distributed()

    def is_balanced(self, force_check: bool = False) -> bool:
        return True

    def balance_(self) -> "DNDarray":
        """No-op: GSPMD keeps arrays balanced (reference dndarray.py:470-508)."""
        return self

    def counts_displs(self) -> Tuple[Tuple[int, ...], Tuple[int, ...]]:
        """Counts/displacements along the split axis (reference dndarray.py:543)."""
        if self.__split is None:
            raise ValueError("Non-distributed DNDarray has no counts and displacements")
        return self.__comm.counts_displs_shape(self.__gshape, self.__split)

    def resplit_(self, axis: Optional[int] = None) -> "DNDarray":
        """In-place redistribution to a new split axis (reference
        dndarray.py:1235-1357: Allgatherv / tile-P2P; here one ``device_put``
        whose resharding collectives XLA chooses).

        Under collective-aware fusion a PENDING recorded chain stays
        recorded: the redistribution becomes a collective node in the DAG
        (``fusion.defer_reshard`` — a sharding constraint the fused
        program's partitioner schedules), so chains spanning a resplit
        compile into one program instead of fencing here. The
        ``collective.reshard`` fault site still fires at record-or-dispatch
        time, before any metadata mutates; ``HEAT_TPU_FUSION_COLLECTIVES=0``
        restores the force-at-collective behavior."""
        axis = sanitize_axis(self.__gshape, axis)
        if axis == self.__split:
            return self
        was_padded = self.padded
        if resilience._ARMED:
            # a preemption mid-redistribution is a classic pod failure mode;
            # the site lets tests prove it surfaces BEFORE the wrapper's
            # metadata is mutated (no half-resharded state)
            resilience.check("collective.reshard")
        payload = self.__array
        if (
            isinstance(payload, fusion.LazyArray)
            and payload._value is None
            and fusion.collectives_active()
        ):
            node = fusion.defer_reshard(
                payload, self.__gshape, self.__split, was_padded, axis, self.__comm
            )
            if node is not None:
                self.__split = axis
                self.__array = node
                fusion.register_root(self)
                return self
            # recording declined (defer_reshard left the breadcrumb): force
            # and reshard eagerly below — today's behavior
        self._force_payload(_T_COLLECTIVE)  # redistribution = collective
        logical = self.larray
        self.__split = axis
        if axis is not None and self.__gshape[axis] % self.__comm.size != 0:
            self.__array = _pad_and_place(logical, axis, self.__comm)
        elif was_padded:
            # the old payload was padded, so ``logical`` is a fresh slice no
            # caller can hold — donate its buffer to the reshard program
            self.__array = _reshard_donating(logical, axis, self.__comm)
        else:
            self.__array = _ensure_split(logical, axis, self.__comm)
        return self

    def redistribute_(self, lshape_map=None, target_map=None) -> "DNDarray":
        """Reference dndarray.py:1029-1233 moves data to an arbitrary ragged
        target map. GSPMD owns the (always-balanced) layout, so only the
        balanced identity map is representable; anything else is rejected."""
        if target_map is not None:
            tm = np.asarray(target_map.larray if isinstance(target_map, DNDarray) else target_map)
            if not np.array_equal(tm, self.__comm.lshape_map(self.__gshape, self.__split)):
                raise NotImplementedError(
                    "arbitrary (ragged) target maps are not representable under GSPMD; "
                    "arrays are always balanced (SURVEY.md §7 design stance)"
                )
        return self

    def get_halo(self, halo_size: int) -> None:
        """Materialize split-axis boundary halos from neighbor devices
        (reference dndarray.py:360-441: Isend/Irecv to split-axis neighbors).

        The TPU rendering is one ``shard_map`` program with two
        ``ppermute`` ring shifts: every device sends its trailing
        ``halo_size`` slice to the next device and its leading slice to the
        previous one; edge devices receive zeros. The received halos are
        cached and consumed by :attr:`array_with_halos` (used by the
        distributed ``convolve`` stencil path, signal.py)."""
        if not isinstance(halo_size, int):
            raise TypeError(f"halo_size needs to be of Python type integer, {type(halo_size)} given")
        if halo_size < 0:
            raise ValueError(f"halo_size needs to be a positive Python integer, {halo_size} given")
        self.__halo_size = halo_size
        self.__halo_cache = None
        if halo_size > 0 and self.__split is not None and self.__comm.size > 1:
            split = self.__split
            p = self.__comm.size
            payload = self.__array
            if (
                isinstance(payload, fusion.LazyArray)
                and payload._value is None
                and fusion.collectives_active()
                and not self.padded
            ):
                # deferred exchange: the ppermute pair records as one
                # multi-output collective node consumed lazily (convolve's
                # stencil path compiles exchange + conv into ONE program);
                # the public array_with_halos still materializes
                block = int(payload.shape[split]) // p
                if 0 < halo_size <= block:
                    if resilience._ARMED:
                        resilience.check("collective.halo")
                    kernel = _halo_exchange_kernel(
                        self.__comm.axis_name, split, halo_size, block, p
                    )
                    nodes = fusion.defer_apply(
                        self.__comm, kernel, (self,),
                        in_splits=(split,), out_split=(split, split),
                    )
                    if nodes is not None:
                        hshape = list(payload.shape)
                        hshape[split] = halo_size * p
                        self.__halo_cache = (
                            fusion.wrap_node(nodes[0], tuple(hshape), split, self),
                            fusion.wrap_node(nodes[1], tuple(hshape), split, self),
                        )
                        return
                else:
                    return  # halo wider than a block: no exchange either way
            phys = self._force_payload(_T_COLLECTIVE)
            block = int(phys.shape[split]) // p
            if 0 < halo_size <= block:
                if resilience._ARMED:
                    resilience.check("collective.halo")
                fn = _halo_program(
                    self.__comm.mesh,
                    self.__comm.axis_name,
                    split,
                    halo_size,
                    tuple(int(s) for s in phys.shape),
                    str(phys.dtype),
                )
                self.__halo_cache = fn(phys)

    @property
    def array_with_halos(self) -> jax.Array:
        """The physical payload with each device's shard extended by the
        halos exchanged in :meth:`get_halo` (reference dndarray.py:332-341):
        a global array of shape ``p * (block + 2*halo)`` along the split axis
        where every device holds ``[from_prev | local | from_next]``. Without
        materialized halos this is the logical global view."""
        halos = getattr(self, "_DNDarray__halo_cache", None)
        if halos is None:
            return self.larray
        from_prev, from_next = halos
        # the payload must land BEFORE the halo wrappers force: the deferred
        # exchange's parent consumes this chain, so forcing it first makes
        # the chain a leaf of the exchange program instead of a recompute
        phys = self.parray
        if isinstance(from_prev, DNDarray):
            # deferred exchange: the PUBLIC property still returns a
            # materialized array (tests pin np.asarray/.shape on it); the
            # lazy consumer seam is _halo_wrappers (signal.convolve)
            from_prev = from_prev._force_payload(_T_COLLECTIVE)
            from_next = from_next._force_payload(_T_COLLECTIVE)
        fn = _halo_concat_program(
            self.__comm.mesh,
            self.__comm.axis_name,
            self.__split,
            tuple(int(s) for s in phys.shape),
            tuple(int(s) for s in from_prev.shape),
            str(phys.dtype),
        )
        return fn(from_prev, phys, from_next)

    def _halo_wrappers(self) -> Optional[tuple]:
        """Internal: the deferred ``(from_prev, from_next)`` halo pair as
        pending DNDarray wrappers — the lazy seam ``signal.convolve`` records
        its stencil against so exchange + conv compile into one program.
        None when :meth:`get_halo` ran eagerly (or found nothing to do)."""
        halos = getattr(self, "_DNDarray__halo_cache", None)
        if halos is not None and isinstance(halos[0], DNDarray):
            return halos
        return None

    @property
    def halo_prev(self) -> Optional[jax.Array]:
        """Boundary slice a previous-neighbor shard would send (reference
        dndarray.py:312-320). Derived from the global view: the trailing
        ``halo_size`` slice along the split axis of the rank-0 shard."""
        hs = getattr(self, "_DNDarray__halo_size", None)
        if not hs or self.__split is None or self.__comm.size < 2:
            return None
        _, _, slices = self.__comm.chunk(self.__gshape, self.__split, rank=0)
        stop = slices[self.__split].stop
        idx = [slice(None)] * len(self.__gshape)
        idx[self.__split] = slice(max(stop - hs, 0), stop)
        return self.larray[tuple(idx)]

    @property
    def halo_next(self) -> Optional[jax.Array]:
        """Boundary slice a next-neighbor shard would send (reference
        dndarray.py:322-330); leading ``halo_size`` slice of the rank-1 shard."""
        hs = getattr(self, "_DNDarray__halo_size", None)
        if not hs or self.__split is None or self.__comm.size < 2:
            return None
        _, _, slices = self.__comm.chunk(self.__gshape, self.__split, rank=1)
        start = slices[self.__split].start
        idx = [slice(None)] * len(self.__gshape)
        idx[self.__split] = slice(start, start + hs)
        return self.larray[tuple(idx)]

    def create_lshape_map(self, force_check: bool = False):
        """Method form of ``lshape_map`` (reference dndarray.py:569-600)."""
        return self.lshape_map

    # ------------------------------------------------------------------
    # conversions
    # ------------------------------------------------------------------
    def astype(self, dtype, copy: bool = True) -> "DNDarray":
        """Cast to a new element type (reference dndarray.py:443-468). Casts
        of a pending recorded chain stay recorded (``fusion.cast`` node)."""
        dtype = types.canonical_heat_type(dtype)
        arr = self.__array
        if isinstance(arr, fusion.LazyArray):
            try:
                casted = fusion.cast(arr, dtype.jax_type())
            except Exception as exc:  # same ONE policy as the defer_* sites
                if not resilience.record_recoverable(exc):
                    raise
                # recording the cast failed: force the chain and cast eagerly
                casted = self.parray.astype(dtype.jax_type())
        else:
            casted = arr.astype(dtype.jax_type())
        if copy:
            out = DNDarray(
                casted, self.__gshape, dtype, self.__split, self.__device, self.__comm
            )
            if isinstance(casted, fusion.LazyArray):
                fusion.register_root(out)  # async-forcing batch candidate
            return out
        self.__array = casted
        self.__dtype = dtype
        if isinstance(casted, fusion.LazyArray):
            fusion.register_root(self)
        return self

    def numpy(self) -> np.ndarray:
        """Gather the global (logical) array to host numpy (reference
        dndarray.py:991-1003); padding never leaves the device."""
        token = self._note_blocking_sync("numpy")
        with health_runtime.watch(
            "sync:numpy", cid=None if token is None else token.get("cid")
        ):
            out = np.asarray(jax.device_get(self.larray))
        telemetry.end_blocking_sync(token)
        return out

    def __array__(self, dtype=None) -> np.ndarray:
        out = self.numpy()
        return out.astype(dtype) if dtype is not None else out

    def item(self):
        """The single scalar value (reference dndarray.py:965)."""
        if self.size != 1:
            raise ValueError("only one-element DNDarrays can be converted to Python scalars")
        token = self._note_blocking_sync("item")
        with health_runtime.watch(
            "sync:item", cid=None if token is None else token.get("cid")
        ):
            out = self.larray.item()
        telemetry.end_blocking_sync(token)
        return out

    def tolist(self, keepsplit: bool = False) -> list:
        return self.numpy().tolist()

    def cpu(self) -> "DNDarray":
        """Copy to the CPU backend (reference dndarray.py:510)."""
        return self._to_device(devices.cpu)

    def tpu(self) -> "DNDarray":
        return self._to_device(devices.tpu)

    gpu = tpu

    def _to_device(self, device) -> "DNDarray":
        device = devices.sanitize_device(device)
        if device == self.__device:
            return self
        comm = MeshCommunication(jax.devices(device.device_type))
        arr = _ensure_split(jnp.asarray(self.numpy()), self.__split, comm)
        return DNDarray(arr, self.__gshape, self.__dtype, self.__split, device, comm)

    # ------------------------------------------------------------------
    # scalar dunder conversions (reference dndarray.py:516-540)
    # ------------------------------------------------------------------
    def __bool__(self) -> bool:
        return bool(self.item())

    def __int__(self) -> int:
        return int(self.item())

    def __float__(self) -> float:
        return float(self.item())

    def __complex__(self) -> complex:
        return complex(self.item())

    def __index__(self) -> int:
        return int(self.item())

    def __len__(self) -> int:
        if self.ndim == 0:
            raise TypeError("len() of unsized object")
        return self.__gshape[0]

    def __iter__(self):
        for i in range(len(self)):
            yield self[i]

    # ------------------------------------------------------------------
    # indexing — global semantics via jax; split bookkeeping simplified
    # (reference dndarray.py:652-908 / 1359-1648 does manual global->local
    # translation; GSPMD makes global indexing native)
    # ------------------------------------------------------------------
    @staticmethod
    def _unwrap_key(key):
        if isinstance(key, DNDarray):
            return key.larray
        if isinstance(key, tuple):
            return tuple(DNDarray._unwrap_key(k) for k in key)
        if isinstance(key, list):
            # numpy fancy-index semantics: a list key is an array index
            # (jax rejects bare sequences, jax#4564); empty lists must be
            # integer-typed or jax rejects the float indexer
            if not key:
                return jnp.asarray([], dtype=jnp.int32)
            return jnp.asarray([DNDarray._unwrap_key(k) for k in key])
        if isinstance(key, np.ndarray):
            return jnp.asarray(key)
        return key

    def _result_split(self, key) -> Optional[int]:
        """Split of an indexing result: follow what happens to the split dim.

        Advanced (boolean-mask / integer-array) keys keep the distribution
        (reference dndarray.py:652-908 translates them globally; here the
        gather output is re-constrained to ``split``): with a single advanced
        key the result's advanced block lands in place — if it consumed the
        split dimension the result is split along the block's first output
        dim, otherwise the split dim's new position is tracked through the
        key. Multiple advanced keys (numpy moves the block to the front, and
        combining them permutes data across devices unpredictably) degrade to
        replicated.
        """
        if self.__split is None:
            return None
        key_t = key if isinstance(key, tuple) else (key,)
        # expand Ellipsis
        if any(k is Ellipsis for k in key_t):
            n_explicit = 0
            for k in key_t:
                if k is Ellipsis or k is None:
                    continue
                if _is_advanced_key(k) and _key_dtype_is_bool(k):
                    n_explicit += _key_ndim(k)
                else:
                    n_explicit += 1
            expanded: list = []
            for k in key_t:
                if k is Ellipsis:
                    expanded.extend([slice(None)] * (self.ndim - n_explicit))
                else:
                    expanded.append(k)
            key_t = tuple(expanded)

        advanced = [k for k in key_t if _is_advanced_key(k)]
        if len(advanced) > 1:
            return None  # numpy front-moves the block; distribution undefined
        out_dim = 0
        in_dim = 0
        for k in key_t:
            if k is None:
                out_dim += 1
                continue
            if _is_advanced_key(k):
                is_bool = _key_dtype_is_bool(k)
                consumed = _key_ndim(k) if is_bool else 1
                produced = 1 if is_bool else _key_ndim(k)
                if in_dim <= self.__split < in_dim + consumed:
                    # the advanced block consumed the split dim: shard the
                    # block's first result dim (0-D int keys drop the dim)
                    return out_dim if produced > 0 else None
                in_dim += consumed
                out_dim += produced
                continue
            if in_dim == self.__split:
                return out_dim if isinstance(k, slice) else None
            if isinstance(k, (int, np.integer)):
                in_dim += 1
            else:  # slice
                in_dim += 1
                out_dim += 1
        # split dim untouched by the key: shift by dropped/inserted dims before it
        return out_dim + (self.__split - in_dim)

    def __getitem__(self, key) -> "DNDarray":
        self._force_payload(_T_INDEXING)
        jkey = DNDarray._unwrap_key(key)
        result = self.larray[jkey]
        split = self._result_split(key) if result.ndim > 0 else None
        if split is not None and split >= result.ndim:
            split = None
        arr = _ensure_split(result, split, self.__comm)
        return DNDarray(
            arr,
            tuple(result.shape),
            types.canonical_heat_type(result.dtype),
            split,
            self.__device,
            self.__comm,
        )

    def __setitem__(self, key, value):
        self._force_payload(_T_INDEXING)
        jkey = DNDarray._unwrap_key(key)
        if isinstance(value, DNDarray):
            value = value.larray
        # numpy setitem semantics: the value is cast to the destination dtype
        if hasattr(value, "dtype") and value.dtype != self.__array.dtype:
            value = jnp.asarray(value).astype(self.__array.dtype)
        new = self.larray.at[jkey].set(value)
        if self.padded:
            self.__array = _pad_and_place(new, self.__split, self.__comm)
        else:
            # ``new`` is a freshly-computed temporary: donate it on reshard
            self.__array = _reshard_donating(new, self.__split, self.__comm)

    def fill_diagonal(self, value) -> "DNDarray":
        """Fill the main diagonal in place (reference dndarray.py:608-650)."""
        if self.ndim != 2:
            raise ValueError("Only 2D tensors supported")
        n = min(self.__gshape)
        idx = jnp.arange(n)
        new = self.larray.at[idx, idx].set(value)
        if self.padded:
            self.__array = _pad_and_place(new, self.__split, self.__comm)
        else:
            self.__array = _reshard_donating(new, self.__split, self.__comm)
        return self

    # ------------------------------------------------------------------
    # operator protocol — delegates to the operator library, mirroring the
    # reference's pattern of module-level functions bound as methods
    # ------------------------------------------------------------------
    def __add__(self, other):
        from . import arithmetics

        return arithmetics.add(self, other)

    def __radd__(self, other):
        from . import arithmetics

        return arithmetics.add(self, other)

    def __sub__(self, other):
        from . import arithmetics

        return arithmetics.sub(self, other)

    def __rsub__(self, other):
        from . import arithmetics

        return arithmetics.sub(other, self)

    def __mul__(self, other):
        from . import arithmetics

        return arithmetics.mul(self, other)

    def __rmul__(self, other):
        from . import arithmetics

        return arithmetics.mul(self, other)

    def __truediv__(self, other):
        from . import arithmetics

        return arithmetics.div(self, other)

    def __rtruediv__(self, other):
        from . import arithmetics

        return arithmetics.div(other, self)

    def __floordiv__(self, other):
        from . import arithmetics

        return arithmetics.floordiv(self, other)

    def __rfloordiv__(self, other):
        from . import arithmetics

        return arithmetics.floordiv(other, self)

    def __mod__(self, other):
        from . import arithmetics

        return arithmetics.mod(self, other)

    def __rmod__(self, other):
        from . import arithmetics

        return arithmetics.mod(other, self)

    def __pow__(self, other):
        from . import arithmetics

        return arithmetics.pow(self, other)

    def __rpow__(self, other):
        from . import arithmetics

        return arithmetics.pow(other, self)

    def __matmul__(self, other):
        from .linalg import basics

        return basics.matmul(self, other)

    def __and__(self, other):
        from . import arithmetics

        return arithmetics.bitwise_and(self, other)

    def __or__(self, other):
        from . import arithmetics

        return arithmetics.bitwise_or(self, other)

    def __xor__(self, other):
        from . import arithmetics

        return arithmetics.bitwise_xor(self, other)

    def __lshift__(self, other):
        from . import arithmetics

        return arithmetics.left_shift(self, other)

    def __rshift__(self, other):
        from . import arithmetics

        return arithmetics.right_shift(self, other)

    def __invert__(self):
        from . import arithmetics

        return arithmetics.invert(self)

    def __neg__(self):
        from . import arithmetics

        return arithmetics.neg(self)

    def __pos__(self):
        from . import arithmetics

        return arithmetics.pos(self)

    def __abs__(self):
        from . import rounding

        return rounding.abs(self)

    def __eq__(self, other):  # type: ignore[override]
        from . import relational

        return relational.eq(self, other)

    def __ne__(self, other):  # type: ignore[override]
        from . import relational

        return relational.ne(self, other)

    def __lt__(self, other):
        from . import relational

        return relational.lt(self, other)

    def __le__(self, other):
        from . import relational

        return relational.le(self, other)

    def __gt__(self, other):
        from . import relational

        return relational.gt(self, other)

    def __ge__(self, other):
        from . import relational

        return relational.ge(self, other)

    __hash__ = None  # type: ignore[assignment]

    # ------------------------------------------------------------------
    # pytree protocol — beyond the reference (which is eager-only)
    # ------------------------------------------------------------------
    def _tree_flatten(self):
        """Flatten to (physical payload, static metadata).

        Registering DNDarray as a pytree makes whole ``ht.*`` pipelines
        compilable with plain ``jax.jit`` (and differentiable with
        ``jax.grad``): the payload becomes the traced leaf while
        gshape/dtype/split stay static aux data. Eager per-op dispatch —
        the reference's only execution model, and ~all of the wall time of
        small ops on a remote TPU (one tunnel round-trip per op) — then
        collapses into one XLA program per pipeline.

        FORCING POINT: a pending recorded chain materializes here, so the
        enclosing trace sees a concrete (or tracer) leaf, never a LazyArray.
        """
        aux = (self.__gshape, self.__dtype, self.__split, self.__device, self.__comm)
        return (self._force_payload(_T_PYTREE),), aux

    @classmethod
    def _tree_unflatten(cls, aux, children):
        """Rebuild from :meth:`_tree_flatten` parts WITHOUT re-deriving
        anything: the payload may be a tracer (under jit) or a sentinel
        (tree_structure probes), so it must not be inspected; it is stored
        at whatever (possibly padded physical) shape it carries."""
        (payload,) = children
        obj = cls.__new__(cls)
        (
            obj._DNDarray__gshape,
            obj._DNDarray__dtype,
            obj._DNDarray__split,
            obj._DNDarray__device,
            obj._DNDarray__comm,
        ) = aux
        obj._DNDarray__balanced = True
        obj._DNDarray__array = payload
        return obj

    # ------------------------------------------------------------------
    # printing (reference heat/core/printing.py)
    # ------------------------------------------------------------------
    def __repr__(self) -> str:
        from . import printing

        return printing.__str__(self)

    __str__ = __repr__


def _is_advanced_key(k) -> bool:
    """True for boolean-mask / integer-array index components (DNDarray,
    numpy / jax arrays, or list keys — numpy fancy-index semantics)."""
    return isinstance(k, (list, np.ndarray, jax.Array)) or isinstance(k, DNDarray)


def _key_dtype_is_bool(k) -> bool:
    if isinstance(k, DNDarray):
        return k.larray.dtype == jnp.bool_
    if isinstance(k, list):
        return len(k) > 0 and isinstance(k[0], (bool, np.bool_))
    return np.asarray(k).dtype == np.bool_ if isinstance(k, np.ndarray) else k.dtype == jnp.bool_


def _key_ndim(k) -> int:
    if isinstance(k, DNDarray):
        return k.ndim
    if isinstance(k, list):
        return np.asarray(k).ndim
    return k.ndim


@functools.lru_cache(maxsize=None)
def _halo_program(mesh, axis: str, split: int, h: int, pshape, dtype_name: str):
    """Cached halo-exchange program: two ppermute ring shifts returning the
    (from_prev, from_next) halo slices per device; edge devices get zeros
    (the TPU rendering of reference dndarray.py:360-441)."""
    from jax.sharding import PartitionSpec

    p = mesh.devices.size
    block = pshape[split] // p

    def spec():
        ent = [None] * len(pshape)
        ent[split] = axis
        return PartitionSpec(*ent)

    def kernel(x):  # local shard: block along split
        lead = jax.lax.slice_in_dim(x, 0, h, axis=split)
        trail = jax.lax.slice_in_dim(x, block - h, block, axis=split)
        # device d+1 receives d's trailing slice; device d-1 receives d's
        # leading slice; unaddressed edges receive zeros
        from_prev = jax.lax.ppermute(trail, axis, [(j, j + 1) for j in range(p - 1)])
        from_next = jax.lax.ppermute(lead, axis, [(j, j - 1) for j in range(1, p)])
        return from_prev, from_next

    return jax.jit(
        jax.shard_map(
            kernel, mesh=mesh, in_specs=spec(), out_specs=(spec(), spec()), check_vma=False
        )
    )


@functools.lru_cache(maxsize=None)
def _halo_exchange_kernel(axis: str, split: int, h: int, block: int, p: int):
    """The halo exchange as an UNJITTED multi-output kernel for the deferred
    path: the same two ppermute ring shifts as :func:`_halo_program`, handed
    to ``fusion.defer_apply`` so the exchange compiles INTO the enclosing
    chain's program instead of dispatching on its own. Cached so repeated
    records keep one function identity (one program-cache key)."""

    def kernel(x):  # local shard: block along split
        lead = jax.lax.slice_in_dim(x, 0, h, axis=split)
        trail = jax.lax.slice_in_dim(x, block - h, block, axis=split)
        from_prev = jax.lax.ppermute(trail, axis, [(j, j + 1) for j in range(p - 1)])
        from_next = jax.lax.ppermute(lead, axis, [(j, j - 1) for j in range(1, p)])
        return from_prev, from_next

    kernel.__name__ = f"halo_exchange_s{split}_h{h}"
    return kernel


@functools.lru_cache(maxsize=None)
def _halo_concat_program(mesh, axis: str, split: int, pshape, hshape, dtype_name: str):
    """Cached per-device ``[from_prev | local | from_next]`` concatenation
    along the split axis (reference array_with_halos, dndarray.py:332-341)."""
    from jax.sharding import PartitionSpec

    def spec():
        ent = [None] * len(pshape)
        ent[split] = axis
        return PartitionSpec(*ent)

    def kernel(prev, x, nxt):
        return jnp.concatenate([prev, x, nxt], axis=split)

    return jax.jit(
        jax.shard_map(
            kernel,
            mesh=mesh,
            in_specs=(spec(), spec(), spec()),
            out_specs=spec(),
            check_vma=False,
        )
    )


@functools.lru_cache(maxsize=None)
def _pad_program(widths: Tuple[Tuple[int, int], ...], target) -> callable:
    """Cached compiled pad-with-out-sharding program (keyed on pad widths and
    the target NamedSharding so repeated ragged wraps never retrace). The
    input is never donated here: a pad's output is strictly larger than its
    input, so XLA cannot reuse the buffer (donation would only warn)."""
    return jax.jit(lambda a: jnp.pad(jnp.asarray(a), widths), out_shardings=target)


@functools.lru_cache(maxsize=None)
def _donating_reshard_program(target) -> callable:
    """Cached jitted identity-with-out-sharding that DONATES its input buffer.

    Used by the in-place mutators (``resplit_`` of a previously-padded
    payload, ``__setitem__`` repads) whose source array is a freshly-created
    temporary no caller can hold: the reshard is same-shape, so XLA reuses
    the donated buffer instead of keeping source and destination alive."""
    return jax.jit(lambda a: a, out_shardings=target, donate_argnums=(0,))


def _reshard_donating(array: jax.Array, split: Optional[int], comm: MeshCommunication) -> jax.Array:
    """Place ``array`` under the ``split`` sharding, donating its buffer.
    Only for freshly-computed temporaries (see ``_donating_reshard_program``);
    tracers and ragged splits fall back to :func:`_ensure_split`."""
    if (
        isinstance(array, jax.core.Tracer)
        or array.ndim == 0
        or (split is not None and array.shape[split] % comm.size != 0)
    ):
        return _ensure_split(array, split, comm)
    return _donating_reshard_program(comm.sharding(array.ndim, split))(array)


def _pad_and_place(array: jax.Array, split: int, comm: MeshCommunication) -> jax.Array:
    """Physically realize a ragged split: zero-pad the split dim of the
    (logical) ``array`` to ``p * ceil(n/p)`` — a *suffix* of the global dim —
    and place the result under the split NamedSharding, so every device holds
    exactly one block-sized shard. One compiled pad-with-out-sharding program;
    no device ever materializes the full array at rest. The reference instead
    carries ragged per-rank chunks (reference dndarray.py:57-60); JAX rejects
    uneven NamedShardings outright, so pad+mask is the TPU rendering
    (SURVEY.md §7)."""
    n = int(array.shape[split])
    p = comm.size
    block = -(-n // p) if n else 0
    pad = block * p - n
    target = comm.sharding(array.ndim, split)
    if pad == 0:  # pragma: no cover - callers guard, kept for safety
        return jax.device_put(array, target)
    widths = [(0, 0)] * array.ndim
    widths[split] = (0, pad)
    return _pad_program(tuple(widths), target)(array)


def _ensure_split(array: jax.Array, split: Optional[int], comm: MeshCommunication) -> jax.Array:
    """Place ``array`` under the sharding implied by ``split`` if it is not
    already there. Eager resharding is one ``device_put`` (XLA collective).

    Dimensions not divisible by the mesh size cannot carry a NamedSharding in
    JAX (device_put/out_shardings/make_array_from_callback all reject them),
    so for a ragged ``split`` the array is returned untouched: the
    ``DNDarray`` constructor (every wrap site funnels through it) realizes
    the distribution physically via :func:`_pad_and_place`.
    """
    if array.ndim == 0:
        split = None
    if split is not None and array.shape[split] % comm.size != 0:
        return array  # ragged: the DNDarray constructor pads + places
    target = comm.sharding(array.ndim, split)
    current = getattr(array, "sharding", None)
    if current is not None:
        try:
            if current.is_equivalent_to(target, array.ndim):
                return array
        except (TypeError, ValueError, AttributeError):
            pass  # sharding types without a comparable form: place anew
    return jax.device_put(array, target)


jax.tree_util.register_pytree_node(
    DNDarray, DNDarray._tree_flatten, DNDarray._tree_unflatten
)

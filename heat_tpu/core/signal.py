"""Signal processing (reference: heat/core/signal.py).

The reference's distributed 1-D convolution exchanges halos between
split-axis neighbors (signal.py:86-130 via dndarray.get_halo :360-441) and
then runs a local conv1d. Under the global view, one sharded XLA convolution
covers both steps: GSPMD inserts the boundary collective-permutes the halo
exchange performed by hand in the reference.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

from . import factories, sanitation, types
from .dndarray import DNDarray, _ensure_split

__all__ = ["convolve"]


def convolve(a, v, mode: str = "full") -> DNDarray:
    """1-D convolution of ``a`` with kernel ``v`` (reference signal.py:16-148)."""
    if not isinstance(a, DNDarray):
        a = factories.array(a)
    if not isinstance(v, DNDarray):
        v = factories.array(v)
    if a.ndim != 1 or v.ndim != 1:
        raise ValueError("Only 1-dimensional input DNDarrays are allowed")
    if mode not in ("full", "same", "valid"):
        raise ValueError(f"Supported modes are 'full', 'same', 'valid', got {mode!r}")
    if mode == "same" and v.shape[0] % 2 == 0:
        raise ValueError("Mode 'same' cannot be used with even-sized kernel")
    if a.shape[0] < v.shape[0]:
        a, v = v, a

    promoted = types.promote_types(a.dtype, v.dtype)
    if types.heat_type_is_exact(promoted):
        promoted = types.promote_types(promoted, types.float32)
    al = a.larray.astype(promoted.jax_type())
    vl = v.larray.astype(promoted.jax_type())
    result = jnp.convolve(al, vl, mode=mode)
    split = a.split
    result = _ensure_split(result, split, a.comm)
    return DNDarray(
        result, tuple(result.shape), types.canonical_heat_type(result.dtype), split, a.device, a.comm
    )

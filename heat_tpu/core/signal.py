"""Signal processing (reference: heat/core/signal.py).

The reference's distributed 1-D convolution exchanges halos between
split-axis neighbors (signal.py:86-130 via dndarray.get_halo :360-441) and
then runs a local conv1d. The TPU rendering keeps exactly that schedule for
the block-aligned case: ``a.get_halo(k//2)`` materializes the neighbor halos
via ppermute (dndarray._halo_program), and a ``shard_map`` kernel runs one
*local* valid-mode convolution per device over ``array_with_halos`` — the
halo exchange is the only communication. Other cases (even kernels, ragged
or replicated inputs, halo wider than a block) run one global XLA
convolution instead.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp

from . import factories, fusion, sanitation, types
from .dndarray import DNDarray, _ensure_split

__all__ = ["convolve"]


@functools.lru_cache(maxsize=None)
def _halo_conv_kernel(k: int):
    """The stencil as an UNJITTED kernel over the deferred halo pair: each
    device concatenates ``[prev | local | next]`` and convolves locally
    (overlap-save). Recorded through ``fusion.defer_apply`` so the halo
    exchange AND the conv compile into the producing chain's one program."""

    def kernel(prev, x, nxt, v):  # (h,), (block,), (h,), (k,) -> (block,)
        return jnp.convolve(jnp.concatenate([prev, x, nxt]), v, mode="valid")

    kernel.__name__ = f"halo_conv_k{k}"
    return kernel


@functools.lru_cache(maxsize=None)
def _halo_conv_program(mesh, axis: str, ext: int, k: int, dtype_name: str):
    """Cached local valid-conv kernel over halo-extended shards: each device
    convolves its ``[prev | local | next]`` slab, producing exactly its own
    ``block`` outputs (overlap-save; reference signal.py:86-130)."""
    from jax.sharding import PartitionSpec as P

    def kernel(x_ext, v):  # (ext,), (k,) -> (ext - k + 1,)
        return jnp.convolve(x_ext, v, mode="valid")

    return jax.jit(
        jax.shard_map(
            kernel,
            mesh=mesh,
            in_specs=(P(axis), P()),
            out_specs=P(axis),
            check_vma=False,
        )
    )


def convolve(a, v, mode: str = "full") -> DNDarray:
    """1-D convolution of ``a`` with kernel ``v`` (reference signal.py:16-148)."""
    if not isinstance(a, DNDarray):
        a = factories.array(a)
    if not isinstance(v, DNDarray):
        v = factories.array(v)
    if a.ndim != 1 or v.ndim != 1:
        raise ValueError("Only 1-dimensional input DNDarrays are allowed")
    if mode not in ("full", "same", "valid"):
        raise ValueError(f"Supported modes are 'full', 'same', 'valid', got {mode!r}")
    if mode == "same" and v.shape[0] % 2 == 0:
        raise ValueError("Mode 'same' cannot be used with even-sized kernel")
    if a.shape[0] < v.shape[0]:
        a, v = v, a

    promoted = types.promote_types(a.dtype, v.dtype)
    if types.heat_type_is_exact(promoted):
        promoted = types.promote_types(promoted, types.float32)
    k = v.shape[0]
    n = a.shape[0]
    p = a.comm.size

    # distributed stencil path (reference signal.py:86-130): odd kernel,
    # same-mode, block-aligned row split — halo exchange + local conv only
    if (
        mode == "same"
        and k % 2 == 1
        and a.split == 0
        and p > 1
        and not a.padded
        and n % p == 0
        and k // 2 <= n // p
        and k // 2 > 0
    ):
        if a.dtype is not promoted:
            a = a.astype(promoted)
        vl = v.larray.astype(promoted.jax_type())
        h = k // 2
        a.get_halo(h)
        halos = a._halo_wrappers()
        if halos is not None:
            # deferred stencil: get_halo recorded the ppermute pair — record
            # the local conv against it, so chain → exchange → conv is ONE
            # cached program forced at the consumer's read
            node = fusion.defer_apply(
                a.comm,
                _halo_conv_kernel(k),
                (halos[0], a, halos[1], vl),
                in_splits=(0, 0, 0, None),
                out_split=0,
            )
            if node is not None:
                return fusion.wrap_node(node, (n,), 0, a)
        ext_global = a.array_with_halos  # (p * (block + 2h),)
        fn = _halo_conv_program(
            a.comm.mesh, a.comm.axis_name, n // p + 2 * h, k, str(ext_global.dtype)
        )
        result = fn(ext_global, vl)
        return DNDarray(
            result,
            tuple(result.shape),
            types.canonical_heat_type(result.dtype),
            0,
            a.device,
            a.comm,
        )

    al = a.larray.astype(promoted.jax_type())
    vl = v.larray.astype(promoted.jax_type())
    result = jnp.convolve(al, vl, mode=mode)
    split = a.split
    result = _ensure_split(result, split, a.comm)
    return DNDarray(
        result, tuple(result.shape), types.canonical_heat_type(result.dtype), split, a.device, a.comm
    )

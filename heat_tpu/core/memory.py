"""Memory operations (reference: heat/core/memory.py)."""

from __future__ import annotations

from .dndarray import DNDarray

__all__ = ["copy", "sanitize_memory_layout"]


def copy(a: DNDarray) -> DNDarray:
    """Deep copy (reference memory.py:13)."""
    if not isinstance(a, DNDarray):
        raise TypeError(f"input needs to be a DNDarray, but was {type(a)}")
    import jax.numpy as jnp

    return DNDarray(jnp.copy(a.larray), a.gshape, a.dtype, a.split, a.device, a.comm)


def sanitize_memory_layout(x, order: str = "C"):
    """Memory-layout normalization (reference memory.py:42). XLA owns physical
    layout on TPU (tiled, not strided), so 'C'/'F' requests are accepted and
    recorded but do not transpose storage."""
    if order not in ("C", "F"):
        raise ValueError(f"expected order to be 'C' or 'F', but was {order}")
    return x

"""Core of the TPU-native distributed tensor framework.

Mirrors the reference's flat re-export layout (heat/core/__init__.py:5-32):
everything is importable as ``heat_tpu.<name>``.
"""

from . import _compat  # install jax compatibility shims FIRST (jax.shard_map)
from .communication import *
from . import communication
from .devices import *
from . import devices
from . import types
from .types import *
from . import version
from .version import __version__
from .constants import *
from .base import *
from .stride_tricks import *
from . import telemetry
from . import resilience
from .resilience import errstate
from . import memledger
from . import health_runtime
from . import tracelens
from . import numlens
from . import fusion
from . import elastic
from . import serving
from . import opsplane
from .dndarray import *
from .factories import *
from .memory import *
from .sanitation import *
from .arithmetics import *
from .relational import *
from .logical import *
from .rounding import *
from .exponential import *
from .trigonometrics import *
from .complex_math import *
from .printing import *
from .statistics import *
from .io import *
from . import io
from .manipulations import *
from .tiling import *
from . import tiling
from .indexing import *
from .signal import *
from . import random
from . import linalg
from .linalg import *

"""Statistical operations (reference: heat/core/statistics.py).

The reference's distributed machinery — custom MPI reduce ops carrying
(value, index) pairs for argmax/argmin (statistics.py:1335-1405), pairwise
moment merging for mean/var/std (``__merge_moments`` :1043-1113), Allgathered
bin counts for percentile (:1406-1675) — all collapses to sharded ``jnp``
reductions: XLA's psum is already deterministic and numerically stable at
these widths, so the merge choreography is not re-implemented.
"""

from __future__ import annotations

import builtins
import functools
from typing import Optional, Sequence, Tuple, Union

import jax
import jax.numpy as jnp
import numpy as np

from . import factories, fusion, sanitation, types
from ._operations import __binary_op as _binary_op
from ._operations import __local_op as _local_op
from ._operations import __reduce_op as _reduce_op
from .communication import sanitize_comm
from .dndarray import DNDarray, _ensure_split
from .stride_tricks import sanitize_axis

__all__ = [
    "argmax",
    "argmin",
    "average",
    "bincount",
    "bucketize",
    "cov",
    "digitize",
    "histc",
    "histogram",
    "kurtosis",
    "max",
    "maximum",
    "mean",
    "median",
    "mpi_argmax",
    "mpi_argmin",
    "min",
    "minimum",
    "percentile",
    "skew",
    "std",
    "var",
]


def _wrap(result: jax.Array, split, ref: DNDarray) -> DNDarray:
    if result.ndim == 0 or (split is not None and split >= result.ndim):
        split = None
    result = _ensure_split(result, split, ref.comm)
    return DNDarray(
        result, tuple(result.shape), types.canonical_heat_type(result.dtype), split, ref.device, ref.comm
    )


def argmax(x: DNDarray, axis: Optional[int] = None, out=None, **kwargs) -> DNDarray:
    """Indices of maximum values (reference statistics.py:37-116; the custom
    (value,index)-pair MPI op :1335-1405 is XLA's native sharded argmax)."""
    return _arg_reduce(jnp.argmax, x, axis, out)


def argmin(x: DNDarray, axis: Optional[int] = None, out=None, **kwargs) -> DNDarray:
    """Indices of minimum values (reference statistics.py:117-196)."""
    return _arg_reduce(jnp.argmin, x, axis, out)


@functools.lru_cache(maxsize=None)
def _arg_reduce_kernel(is_max: bool, axis: int, axis_name: str, block: int, size: int):
    """The split-crossing argmax/argmin shard_map kernel, cached per layout:
    a STABLE function identity (unlike a per-call closure) keys the fusion
    program cache and the retrace ledger correctly, so deferred argreduce
    chains hit compiled code in steady state."""
    from . import communication

    red = jnp.max if is_max else jnp.min
    arg = jnp.argmax if is_max else jnp.argmin
    combiner = mpi_argmax if is_max else mpi_argmin

    def kernel(xs):
        lv = red(xs, axis=axis)
        li = arg(xs, axis=axis) + jax.lax.axis_index(axis_name) * block
        _, gi = communication.allreduce((lv, li), axis_name, op=combiner, size=size)
        return gi

    kernel.__name__ = "argmax" if is_max else "argmin"
    return kernel


def _arg_reduce(op, x, axis, out):
    sanitation.sanitize_in(x)
    axis = sanitize_axis(x.shape, axis)
    # distributed schedule for a reduction ACROSS the split axis: local
    # (value, global-index) partials merged with the mpi_argmax/mpi_argmin
    # combiner through one allreduce — the reference's custom MPI reduce op
    # (reference statistics.py:1335-1405) riding MeshCommunication.allreduce.
    # Under collective-aware fusion the kernel records into the op-chain DAG
    # (fusion.defer_apply) instead of dispatching its own program, so
    # chain→argmax→chain compiles into ONE cached sharded program.
    if (
        isinstance(axis, int)
        and x.split == axis
        and not x.padded
        and x.comm.size > 1
    ):
        comm = x.comm
        block = x.shape[axis] // comm.size
        kernel = _arg_reduce_kernel(
            op is jnp.argmax, axis, comm.axis_name, block, comm.size
        )
        if out is None and fusion.active() and fusion.collectives_active():
            node = fusion.defer_apply(comm, kernel, (x,), (axis,), None)
            if node is not None:
                node = fusion.cast(node, types.index_dtype())
                return fusion.wrap_node(node, node.shape, None, x)
            # defer_apply left its own unfused breadcrumb: dispatch eagerly
        result = comm.apply(kernel, x.larray, in_splits=[axis], out_splits=None)
        result = result.astype(types.index_dtype())
        split = None
        ret = _wrap(result, split, x)
        if out is not None:
            sanitation.sanitize_out(out, ret.shape, ret.split, ret.device)
            out._replace(ret.larray.astype(out.dtype.jax_type()), ret.split)
            return out
        return ret
    result = op(x.larray, axis=axis).astype(types.index_dtype())
    if axis is None:
        split = None
    else:
        split = x.split
        if split is not None:
            if split == axis:
                split = None
            elif split > axis:
                split -= 1
    ret = _wrap(result, split, x)
    if out is not None:
        sanitation.sanitize_out(out, ret.shape, ret.split, ret.device)
        out._replace(ret.larray.astype(out.dtype.jax_type()), ret.split)
        return out
    return ret


def average(
    x: DNDarray, axis=None, weights: Optional[DNDarray] = None, returned: bool = False
):
    """Weighted average (reference statistics.py:197-316)."""
    sanitation.sanitize_in(x)
    if weights is None:
        result = mean(x, axis)
        if returned:
            cnt = np.prod(x.shape) if axis is None else _axis_count(x.shape, axis)
            wsum = factories.full_like(result, float(cnt))
            return result, wsum
        return result
    if weights.shape != x.shape:
        if axis is None or isinstance(axis, tuple):
            raise TypeError("Axis must be specified when shapes of x and weights differ.")
        if weights.ndim != 1:
            raise TypeError("1D weights expected when shapes of x and weights differ.")
        if weights.shape[0] != x.shape[axis]:
            raise ValueError("Length of weights not compatible with specified axis.")
        wl = weights.larray
        shape = [1] * x.ndim
        shape[axis] = -1
        wl = wl.reshape(shape)
    else:
        wl = weights.larray
    wsum = jnp.sum(jnp.broadcast_to(wl, x.shape), axis=axis)
    if bool(jnp.any(wsum == 0)):
        raise ZeroDivisionError("Weights sum to zero, can't be normalized")
    num = jnp.sum(x.larray * wl, axis=axis)
    result = num / wsum
    split = _reduced_split(x, axis)
    ret = _wrap(result, split, x)
    if returned:
        return ret, _wrap(jnp.broadcast_to(wsum, result.shape), split, x)
    return ret


def _axis_count(shape, axis):
    if isinstance(axis, tuple):
        out = 1
        for ax in axis:
            out *= shape[ax]
        return out
    return shape[axis]


def _reduced_split(x: DNDarray, axis, keepdims: bool = False):
    if x.split is None or axis is None:
        return None
    axes = (axis,) if isinstance(axis, int) else tuple(axis)
    axes = tuple(sanitize_axis(x.shape, a) for a in axes)
    if x.split in axes:
        return None
    if keepdims:
        return x.split
    return x.split - sum(1 for a in axes if a < x.split)


_ONEHOT_BINCOUNT_MAX = 1024


def _fast_bincount(idx: jax.Array, length: int, weights: Optional[jax.Array] = None) -> jax.Array:
    """Counting core shared by bincount/histc/histogram.

    XLA lowers ``.at[].add`` scatters on TPU to a slow sort-based expansion
    (~17x slower than needed, measured on v5e); for a moderate number of bins
    the count is an MXU/VPU-shaped reduction instead: a one-hot compare that
    XLA fuses into the sum without materializing the (n, length) matrix.
    Falls back to the scatter path when bins are many or on CPU, where
    scatter-add is native.
    """
    use_onehot = length <= _ONEHOT_BINCOUNT_MAX and jax.default_backend() in ("tpu", "axon")
    if not use_onehot:
        return jnp.bincount(idx, weights=weights, length=length)
    if weights is None:
        # int32 accumulation keeps counts exact past f32's 2^24 integer range
        oh = jax.nn.one_hot(idx, length, dtype=jnp.int32)
        return jnp.sum(oh, axis=0).astype(
            jnp.int64 if jax.config.read("jax_enable_x64") else jnp.int32
        )
    oh = jax.nn.one_hot(idx, length, dtype=weights.dtype)
    return weights @ oh  # (n,) @ (n, length): MXU


def bincount(x: DNDarray, weights: Optional[DNDarray] = None, minlength: int = 0) -> DNDarray:
    """Count occurrences of non-negative ints (reference statistics.py:317-374)."""
    sanitation.sanitize_in(x)
    if not types.heat_type_is_exact(x.dtype):
        raise TypeError(f"input must be integer type, got {x.dtype}")
    n = int(x.size)
    length = builtins.max(minlength, (int(jnp.max(x.larray)) + 1) if n else minlength)
    w = weights.larray.reshape(-1) if weights is not None else None
    result = _fast_bincount(x.larray.reshape(-1), length, w)
    if weights is None:
        result = result.astype(types.index_dtype())
    return _wrap(result, None, x)


def bucketize(
    input: DNDarray, boundaries, right: bool = False, out_int32: bool = False, out=None
) -> DNDarray:
    """Bucket index for each element (reference statistics.py:375-443)."""
    sanitation.sanitize_in(input)
    b = boundaries.larray if isinstance(boundaries, DNDarray) else jnp.asarray(boundaries)
    # torch semantics: right=False places v at the first boundary >= v
    # (numpy side='left'); right=True at the first boundary > v (side='right')
    side = "right" if right else "left"
    result = jnp.searchsorted(b, input.larray.reshape(-1), side=side).reshape(input.shape)
    result = result.astype(jnp.int32 if out_int32 else types.index_dtype())
    ret = _wrap(result, input.split, input)
    if out is not None:
        out._replace(ret.larray, ret.split)
        return out
    return ret


def cov(
    m: DNDarray, y: Optional[DNDarray] = None, rowvar: bool = True, bias: bool = False, ddof: Optional[int] = None
) -> DNDarray:
    """Covariance matrix estimate (reference statistics.py:444-525)."""
    if ddof is not None and not isinstance(ddof, int):
        raise TypeError("ddof must be integer")
    sanitation.sanitize_in(m)
    if m.ndim > 2:
        raise ValueError("m has more than 2 dimensions")
    x = m.larray.astype(jnp.promote_types(m.dtype.jax_type(), jnp.float32))
    if x.ndim == 1:
        x = x[None, :]
    if not rowvar and x.shape[0] != 1:
        x = x.T
    if y is not None:
        sanitation.sanitize_in(y)
        if y.ndim > 2:
            raise ValueError("y has more than 2 dimensions")
        yl = y.larray.astype(x.dtype)
        if yl.ndim == 1:
            yl = yl[None, :]
        if not rowvar and yl.shape[0] != 1:
            yl = yl.T
        x = jnp.concatenate([x, yl], axis=0)
    if ddof is None:
        ddof = 0 if bias else 1
    norm = x.shape[1] - ddof
    xm = x - jnp.mean(x, axis=1, keepdims=True)
    result = (xm @ jnp.conj(xm.T)) / norm
    return _wrap(jnp.squeeze(result), None, m)


def digitize(x: DNDarray, bins, right: bool = False) -> DNDarray:
    """Bin index for each element, numpy semantics (reference statistics.py:526-590)."""
    sanitation.sanitize_in(x)
    b = bins.larray if isinstance(bins, DNDarray) else jnp.asarray(bins)
    result = jnp.digitize(x.larray, b, right=right)
    return _wrap(result.astype(types.index_dtype()), x.split, x)


def histc(input: DNDarray, bins: int = 100, min: float = 0.0, max: float = 0.0, out=None) -> DNDarray:
    """Histogram with equal-width bins (reference statistics.py:591-651).

    The data-derived default range stays on device (traced scalars), so the
    op composes under ``jax.jit`` pipelines."""
    sanitation.sanitize_in(input)
    data = input.larray
    if sanitation.is_concrete(data):
        # eager: Python float64 range arithmetic (the degenerate ±1
        # expansion must not round away at large magnitudes — f32 ulp at
        # 1e8 is 8)
        lo, hi = float(min), float(max)
        if lo == 0.0 and hi == 0.0:
            lo = float(jnp.min(data))
            hi = float(jnp.max(data))
        if lo == hi:
            lo -= 1.0
            hi += 1.0
    else:
        # under a jit trace the data-derived range stays on device, in the
        # widest float the backend offers (f64 under x64, else f32 — the
        # degenerate expansion can round away at magnitudes ≥ 2^24 there)
        wdt = jnp.promote_types(data.dtype, jnp.float32)
        if float(min) == 0.0 and float(max) == 0.0:
            lo = jnp.min(data).astype(wdt)
            hi = jnp.max(data).astype(wdt)
        else:
            lo = jnp.asarray(float(min), wdt)
            hi = jnp.asarray(float(max), wdt)
        degenerate = lo == hi
        lo = jnp.where(degenerate, lo - 1.0, lo)
        hi = jnp.where(degenerate, hi + 1.0, hi)
    # torch.histc excludes out-of-range elements; bin index is direct
    # arithmetic on the equal-width grid, counted scatter-free
    data = data.reshape(-1)
    mask = (data >= lo) & (data <= hi)
    fdata = data.astype(jnp.float32) if not types.heat_type_is_inexact(input.dtype) else data
    idx = jnp.floor((fdata - lo) / (hi - lo) * bins).astype(jnp.int32)
    idx = jnp.clip(idx, 0, bins - 1)
    hist = _fast_bincount(idx, bins, mask.astype(fdata.dtype))
    ret = _wrap(hist.astype(input.dtype.jax_type()), None, input)
    if out is not None:
        out._replace(ret.larray, None)
        return out
    return ret


def histogram(a: DNDarray, bins: int = 10, range=None, normed=None, weights=None, density=None):
    """numpy-style histogram (reference statistics.py:652-699); counted via
    the scatter-free ``_fast_bincount`` on the searchsorted bin indices."""
    sanitation.sanitize_in(a)
    w = weights.larray.reshape(-1) if isinstance(weights, DNDarray) else (
        jnp.asarray(weights).reshape(-1) if weights is not None else None
    )
    data = a.larray.reshape(-1)
    if isinstance(bins, int) and bins <= _ONEHOT_BINCOUNT_MAX:
        edges = jnp.histogram_bin_edges(data, bins=bins, range=range)
        fdata = data.astype(edges.dtype)
        idx = jnp.clip(jnp.searchsorted(edges, fdata, side="right") - 1, 0, bins - 1)
        valid = (fdata >= edges[0]) & (fdata <= edges[-1])
        wv = valid.astype(edges.dtype) if w is None else jnp.where(valid, w, 0).astype(edges.dtype)
        hist = _fast_bincount(idx, bins, wv)
        if w is None:
            hist = hist.astype(types.index_dtype())
        if density:
            widths = jnp.diff(edges)
            hist = hist.astype(edges.dtype) / widths / jnp.sum(hist).astype(edges.dtype)
    else:
        hist, edges = jnp.histogram(data, bins=bins, range=range, weights=w, density=density)
    return _wrap(hist, None, a), _wrap(edges, None, a)


def kurtosis(x: DNDarray, axis: Optional[int] = None, unbiased: bool = True, Fischer: bool = True) -> DNDarray:
    """Kurtosis (4th central moment ratio) (reference statistics.py:700-784).

    ``unbiased`` applies the standard sample bias correction.
    """
    return _moment_stat(x, axis, order=4, unbiased=unbiased, fischer=Fischer)


def skew(x: DNDarray, axis: Optional[int] = None, unbiased: bool = True) -> DNDarray:
    """Skewness (3rd central moment ratio) (reference statistics.py:1860-1935)."""
    return _moment_stat(x, axis, order=3, unbiased=unbiased)


def _moment_stat(x, axis, order, unbiased, fischer=True):
    sanitation.sanitize_in(x)
    axis = sanitize_axis(x.shape, axis)
    if isinstance(axis, tuple):
        raise TypeError("axis must be None or an int")
    data = x.larray.astype(jnp.promote_types(x.dtype.jax_type(), jnp.float32))
    n = data.size if axis is None else data.shape[axis]
    mu = jnp.mean(data, axis=axis, keepdims=True)
    centered = data - mu
    m2 = jnp.mean(centered**2, axis=axis)
    mk = jnp.mean(centered**order, axis=axis)
    if order == 3:
        g = mk / jnp.power(m2, 1.5)
        if unbiased:
            g = g * jnp.sqrt(n * (n - 1)) / (n - 2)
    else:
        g = mk / (m2**2)
        if unbiased:
            g = ((n**2 - 1) * g - 3 * (n - 1) ** 2) / ((n - 2) * (n - 3)) + 3
        if fischer:
            g = g - 3
    return _wrap(jnp.asarray(g), _reduced_split(x, axis), x)


@functools.lru_cache(maxsize=None)
def _nan_propagating(op):
    """numpy max/min semantics: any NaN in the reduced window wins.

    XLA's *local* maximum propagates NaN, but the cross-device all-reduce
    combiner does not (C-max semantics — the reference's MPI.MAX has the
    identical hole), so a sharded reduce could silently drop NaN depending
    on the mesh size. One explicit isnan any-reduction restores the numpy
    contract deterministically; the pad-aware fast path stays safe because
    pad-slot NaNs only ever land in pad slots of the result.

    The wrapper is cached per ``op`` so its identity is stable call-to-call —
    the fusion engine's program cache keys on the operation object, and a
    fresh closure per ``ht.max`` call would force a retrace every time.
    """

    def fn(src, axis=None, keepdims=False, **kw):
        res = op(src, axis=axis, keepdims=keepdims, **kw)
        if jnp.issubdtype(src.dtype, jnp.floating):
            has_nan = jnp.any(jnp.isnan(src), axis=axis, keepdims=keepdims)
            res = jnp.where(has_nan, jnp.asarray(jnp.nan, res.dtype), res)
        return res

    return fn


def _reduction_crosses_split(x: DNDarray, axis) -> bool:
    if x.split is None:
        return False
    if axis is None:
        return True
    axes = (axis,) if isinstance(axis, int) else tuple(axis)
    ndim = x.ndim
    return any((a % ndim if ndim else a) == x.split for a in axes)


def max(x: DNDarray, axis=None, out=None, keepdims=False, keepdim=None) -> DNDarray:
    """Maximum along axis (reference statistics.py:785-901). ``keepdim`` is
    the reference's torch-style alias for ``keepdims``."""
    # XLA's local max propagates NaN; only the cross-device combine needs
    # the explicit pass (see _nan_propagating) — skip the extra traffic
    # for purely-local reductions
    op = _nan_propagating(jnp.max) if _reduction_crosses_split(x, axis) else jnp.max
    return _reduce_op(op, x, axis, out=out, keepdims=keepdims if keepdim is None else keepdim)


def maximum(x1: DNDarray, x2: DNDarray, out=None) -> DNDarray:
    """Elementwise maximum (reference statistics.py:902-940)."""
    return _binary_op(jnp.maximum, x1, x2, out=out)


def mean(x: DNDarray, axis=None, keepdims: bool = False) -> DNDarray:
    """Arithmetic mean (reference statistics.py:941-1007: local torch.mean +
    Allreduce of (mu, n) pairs with sequential merging; one sharded jnp.mean
    here). Routes through the L3 reduce engine, so under the fusion recorder
    a mean at the end of an op chain stays in the chain's single program."""
    if types.heat_type_is_exact(getattr(x, "dtype", types.float32)):
        x = x.astype(types.promote_types(x.dtype, types.float32))
    return _reduce_op(jnp.mean, x, axis, keepdims=keepdims)


def median(x: DNDarray, axis: Optional[int] = None, keepdims: bool = False, keepdim=None) -> DNDarray:
    """Median (reference statistics.py:1008-1042, via percentile's distributed
    bin protocol :1406-1675; a sharded sort-based kernel here)."""
    if keepdim is not None:
        keepdims = keepdim  # torch-style alias of the reference
    sanitation.sanitize_in(x)
    axis = sanitize_axis(x.shape, axis)
    if axis is None and x.split is not None and not x.padded:
        return percentile(x, 50.0, keepdims=keepdims)  # gather-free bisection
    data = x.larray
    if types.heat_type_is_exact(x.dtype):
        data = data.astype(types.promote_types(x.dtype, types.float32).jax_type())
    result = jnp.median(data, axis=axis, keepdims=keepdims)
    return _wrap(result, _reduced_split(x, axis, keepdims), x)


def min(x: DNDarray, axis=None, out=None, keepdims=False, keepdim=None) -> DNDarray:
    """Minimum along axis (reference statistics.py:1114-1230). ``keepdim`` is
    the reference's torch-style alias for ``keepdims``."""
    op = _nan_propagating(jnp.min) if _reduction_crosses_split(x, axis) else jnp.min
    return _reduce_op(op, x, axis, out=out, keepdims=keepdims if keepdim is None else keepdim)


def minimum(x1: DNDarray, x2: DNDarray, out=None) -> DNDarray:
    """Elementwise minimum (reference statistics.py:1231-1269)."""
    return _binary_op(jnp.minimum, x1, x2, out=out)


@jax.jit
def _order_stats_bisect(x: jax.Array, ranks: jax.Array) -> jax.Array:
    """Exact order statistics of the flat sharded array ``x`` by bisection on
    the VALUE space: each step counts ``x <= mid`` — a sharded reduction
    (local partial + psum), never a gather — and halves the bracket. The
    k-th order statistic is the smallest v with count(x <= v) >= k+1, which
    the upper bracket converges to within float precision. This is the TPU
    rendering of the reference's bin-count percentile protocol (reference
    statistics.py:1406-1675: Allgather of local bin counts + refinement);
    memory stays O(n/p) per device at any scale."""
    iters = 100 if x.dtype == jnp.float64 else 64
    lo = jnp.min(x)
    hi = jnp.max(x)
    los = jnp.full(ranks.shape, lo, x.dtype)
    his = jnp.full(ranks.shape, hi, x.dtype)

    def body(_, carry):
        los, his = carry
        mid = (los + his) * 0.5
        cnt = jnp.sum(x[None, :] <= mid[:, None], axis=1)
        ge = cnt >= ranks + 1
        return jnp.where(ge, los, mid), jnp.where(ge, mid, his)

    _, his = jax.lax.fori_loop(0, iters, body, (los, his))
    return his


def percentile(
    x: DNDarray,
    q,
    axis: Optional[int] = None,
    out=None,
    interpolation: str = "linear",
    keepdims: bool = False,
    keepdim=None,
) -> DNDarray:
    """q-th percentile (reference statistics.py:1406-1675: Allgather of local
    bin counts + refinement).

    Distributed flat percentiles (``axis=None`` over a split array) run the
    gather-free bisection kernel :func:`_order_stats_bisect`; other cases use
    one XLA quantile kernel over the logical array. ``keepdim`` is the
    reference's torch-style alias for ``keepdims``."""
    if keepdim is not None:
        keepdims = keepdim
    sanitation.sanitize_in(x)
    axis = sanitize_axis(x.shape, axis)
    if interpolation not in ("linear", "lower", "higher", "midpoint", "nearest"):
        raise ValueError(
            "interpolation must be 'linear', 'lower', 'higher', 'midpoint', or 'nearest'"
        )
    qa = jnp.asarray(q, dtype=jnp.float64 if jax.config.jax_enable_x64 else jnp.float32)
    data = x.larray
    if types.heat_type_is_exact(x.dtype):
        data = data.astype(types.promote_types(x.dtype, types.float32).jax_type())

    if axis is None and x.split is not None and not x.padded:
        n = x.size
        flat = data.reshape(-1)
        pos = qa / 100.0 * (n - 1)
        idt = jnp.int64 if jax.config.jax_enable_x64 else jnp.int32
        lower = jnp.floor(pos).astype(idt)
        upper = jnp.ceil(pos).astype(idt)
        ranks = jnp.concatenate([jnp.atleast_1d(lower).ravel(), jnp.atleast_1d(upper).ravel()])
        stats = _order_stats_bisect(flat, ranks)
        m = ranks.shape[0] // 2
        lo_v = stats[:m].reshape(jnp.shape(qa))
        hi_v = stats[m:].reshape(jnp.shape(qa))
        frac = (pos - jnp.floor(pos)).astype(data.dtype)
        if interpolation == "linear":
            result = lo_v + (hi_v - lo_v) * frac
        elif interpolation == "lower":
            result = lo_v
        elif interpolation == "higher":
            result = hi_v
        elif interpolation == "midpoint":
            result = (lo_v + hi_v) * 0.5
        else:  # nearest — numpy rounds half-to-even
            result = jnp.where(jnp.round(pos) <= jnp.floor(pos), lo_v, hi_v)
        if keepdims:
            result = result.reshape(jnp.shape(result) + (1,) * x.ndim)
        ret = _wrap(jnp.asarray(result), None, x)
    else:
        result = jnp.percentile(data, qa, axis=axis, method=interpolation, keepdims=keepdims)
        ret = _wrap(result, None, x)
    if out is not None:
        out._replace(ret.larray.astype(out.dtype.jax_type()), ret.split)
        return out
    return ret


def std(x: DNDarray, axis=None, ddof: int = 0, **kwargs) -> DNDarray:
    """Standard deviation (reference statistics.py:1936-1996). The sqrt goes
    through the L3 local engine so var+sqrt stay one recorded chain."""
    v = var(x, axis, ddof=ddof, **kwargs)
    return _local_op(jnp.sqrt, v, no_cast=True)


def var(x: DNDarray, axis=None, ddof: int = 0, **kwargs) -> DNDarray:
    """Variance (reference statistics.py:2046-2126; pairwise moment merging
    __merge_moments :1043-1113 replaced by one sharded jnp.var)."""
    sanitation.sanitize_in(x)
    if not isinstance(ddof, int):
        raise TypeError(f"ddof must be integer, is {type(ddof)}")
    if ddof not in (0, 1):
        raise ValueError("Only ddof=0 or ddof=1 is supported")
    if kwargs.get("bessel") is not None:
        ddof = 1 if kwargs["bessel"] else 0
    keepdims = bool(kwargs.get("keepdims", False))
    if types.heat_type_is_exact(x.dtype):
        x = x.astype(types.promote_types(x.dtype, types.float32))
    return _reduce_op(jnp.var, x, axis, keepdims=keepdims, ddof=ddof)


def mpi_argmax(a, b):
    """Combiner merging two ``(values, indices)`` pairs to the elementwise max
    and its global index — the pure-JAX equivalent of the reference's custom
    MPI reduce op (reference statistics.py:1335-1370). Usable as the combine
    fn of a ``lax.psum``-style tree or ``jax.lax.reduce`` over shards."""
    av, ai = a
    bv, bi = b
    # NaN-aware (numpy argmax returns the first NaN's index): a NaN side
    # wins; both-NaN keeps the lower-index accumulator. No-op for ints.
    a_nan = jnp.isnan(av) if jnp.issubdtype(av.dtype, jnp.floating) else jnp.zeros_like(av, bool)
    b_nan = jnp.isnan(bv) if jnp.issubdtype(bv.dtype, jnp.floating) else jnp.zeros_like(bv, bool)
    take_b = ((bv > av) | b_nan) & ~a_nan
    return jnp.where(take_b, bv, av), jnp.where(take_b, bi, ai)


def mpi_argmin(a, b):
    """Elementwise-min combiner over ``(values, indices)`` pairs
    (reference statistics.py:1371-1405)."""
    av, ai = a
    bv, bi = b
    a_nan = jnp.isnan(av) if jnp.issubdtype(av.dtype, jnp.floating) else jnp.zeros_like(av, bool)
    b_nan = jnp.isnan(bv) if jnp.issubdtype(bv.dtype, jnp.floating) else jnp.zeros_like(bv, bool)
    take_b = ((bv < av) | b_nan) & ~a_nan
    return jnp.where(take_b, bv, av), jnp.where(take_b, bi, ai)

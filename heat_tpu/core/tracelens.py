"""Post-hoc trace analytics: critical-path profiler, cross-host straggler
attribution, and automatic slowdown diagnosis (``ht.tracelens``).

The observability stack records everything — cid-correlated timeline events,
program keys, async dispatch→sync pairs, merged multi-host Perfetto traces —
but a human still has to scroll the trace to answer "why is this workload
slow". This module computes the verdict: :func:`analyze` consumes the
existing timeline (the live ``telemetry`` state, an exported/merged Chrome
trace document, a file path, or a flight-recorder ring) and produces a
ranked, machine-checkable diagnosis with four parts:

1. **Time attribution** — every wall-clock microsecond of the analyzed
   window is assigned to a bucket, overall and per program key, with an
   explicit ``unattributed`` remainder so the accounting is falsifiable:

   * ``compile``        — cid-joined compile→dispatch intervals (XLA builds)
   * ``dispatch_queue`` — host time from noting a pending chain to the
     program call returning (record walk, batching, enqueue)
   * ``device_execute`` — blocking-sync wait joined to an in-flight dispatch
     via cid: the host observes the device executing
   * ``collective``     — blocking syncs whose trigger is a collective
   * ``sync_wait``      — blocking syncs with no joined dispatch (drains,
     degraded replays)
   * ``host_async``     — uncovered time with a dispatch in flight (healthy
     host/device overlap)
   * ``host_gap``       — uncovered time with nothing in flight: the device
     is provably idle while the host computes

2. **Critical-path extraction** — the longest serialized chain of blocking
   segments through the window, an ordered list of (bucket, dur, program
   key, cid), so "what bounds this workload" is one call.

3. **Cross-host straggler/skew attribution** — on merged traces, per-host
   clock offsets are estimated from the earliest matched collective events
   (per-occurrence matching, robust to cid drift across hosts), then
   per-collective arrival skew names the straggling host; an injected
   per-host delay fault (``trace.hostdelay``) must reproduce the
   ``tracelens.straggler`` finding.

4. **Anti-pattern detectors** — sync storm, retrace storm, reshard
   ping-pong, device-idle gaps — each a structured :class:`Finding` with
   severity and fix hint.

Pure post-hoc: nothing here forces a pending chain, initializes a backend,
or touches the dispatch hot path. The CLI front end is
``python -m heat_tpu.telemetry analyze``.
"""

from __future__ import annotations

import bisect
import json
import math
import os
from dataclasses import dataclass, field
from typing import Any, Dict, List, Optional, Tuple

__all__ = [
    "Finding",
    "TraceIncompleteError",
    "analyze",
    "diagnose",
    "diff",
    "load_analysis",
    "render",
]

#: findings schema version (diff refuses to compare across major bumps)
SCHEMA = 1

#: attribution buckets, in sweep priority order (highest wins a segment)
_BUCKET_PRIORITY = {
    "compile": 6,
    "device_execute": 5,
    "collective": 5,
    "sync_wait": 5,
    "dispatch_queue": 4,
    "host_async": 2,
    "host_gap": 1,
}

#: buckets on which the host is blocked — the critical-path candidate set
_BLOCKING_BUCKETS = ("compile", "dispatch_queue", "device_execute", "collective", "sync_wait")

# detector defaults (overridable per analyze() call)
_SYNC_STORM_K = int(os.environ.get("HEAT_TPU_TRACELENS_SYNC_STORM_K", "24"))
_SYNC_STORM_WINDOW_S = 1.0
_RETRACE_STORM_K = int(os.environ.get("HEAT_TPU_TRACELENS_RETRACE_K", "4"))
_IDLE_GAP_MS = float(os.environ.get("HEAT_TPU_TRACELENS_IDLE_GAP_MS", "250"))
_IDLE_GAP_PCT = 50.0  # host_gap share of window that escalates to a warning
_STRAGGLER_MS = float(os.environ.get("HEAT_TPU_TRACELENS_STRAGGLER_MS", "5"))
_MIN_MATCHED_COLLECTIVES = 3
_MAX_PATH_STEPS = 64


class TraceIncompleteError(ValueError):
    """The analyzed window dropped events past the timeline cap — attribution
    over a truncated window would silently lie. Re-run with a larger
    ``HEAT_TPU_TELEMETRY_EVENTS`` cap, or pass ``allow_partial=True``
    (CLI ``--allow-partial``) to analyze anyway with a loud caveat."""


@dataclass
class Finding:
    """One diagnosis: rule id, severity, message, fix hint — the trace-level
    twin of the static analyzer's ``engine.Finding``."""

    rule: str
    severity: str  # "error" | "warning" | "info"
    message: str
    hint: str = ""
    host: Optional[int] = None
    data: Dict[str, Any] = field(default_factory=dict)

    def as_dict(self) -> Dict[str, Any]:
        doc: Dict[str, Any] = {
            "rule": self.rule,
            "severity": self.severity,
            "message": self.message,
            "hint": self.hint,
        }
        if self.host is not None:
            doc["host"] = self.host
        if self.data:
            doc["data"] = dict(self.data)
        return doc


# ----------------------------------------------------------------------
# normalization: every input shape -> per-host raw event lists (seconds)
# ----------------------------------------------------------------------
def _finite(v) -> Optional[float]:
    try:
        f = float(v)
    except (TypeError, ValueError):
        return None
    return f if math.isfinite(f) else None


def _from_perfetto(doc: dict) -> Tuple[Dict[int, List[dict]], int]:
    """Invert the exporter: a Chrome trace document (one host or merged)
    back to per-pid raw event lists with seconds timestamps. Malformed
    events are skipped — their time lands in ``unattributed``."""
    hosts: Dict[int, List[dict]] = {}
    # B/E pairing stacks for span/timer reconstruction, per (pid, cat, name)
    open_frames: Dict[tuple, List[float]] = {}
    for ev in doc.get("traceEvents", []):
        if not isinstance(ev, dict):
            continue
        ph = ev.get("ph")
        if ph in ("M", "C", "b", "e", None):
            continue  # meta/counter rows; async pairs are re-derived from cids
        pid = ev.get("pid", 0)
        pid = pid if isinstance(pid, int) else 0
        ts = _finite(ev.get("ts"))
        if ts is None:
            continue
        ts /= 1e6  # exporter stamps microseconds
        cat = ev.get("cat")
        name = str(ev.get("name", ""))
        args = ev.get("args") if isinstance(ev.get("args"), dict) else {}
        out = hosts.setdefault(pid, [])
        if ph == "B":
            open_frames.setdefault((pid, cat, name), []).append(ts)
            if cat == "span":
                out.append({"kind": "span_begin", "ts": ts, "name": name})
        elif ph == "E":
            stack = open_frames.get((pid, cat, name))
            start = stack.pop() if stack else None
            if cat == "span":
                dur = (ts - start) if start is not None else None
                out.append({"kind": "span_end", "ts": ts, "name": name, "dur": dur})
            elif cat == "timer" and start is not None:
                out.append({"kind": "timer", "ts": ts, "name": name, "dur": ts - start})
        elif ph == "X" and cat == "sync":
            dur = _finite(ev.get("dur"))
            rec = {
                "kind": "blocking_sync",
                "ts": ts,
                "where": args.get("where"),
                "cid": args.get("cid"),
            }
            if dur is not None:
                rec["dur"] = dur / 1e6
            out.append(rec)
        elif ph == "i":
            if cat == "sync":
                out.append({"kind": "blocking_sync", "ts": ts,
                            "where": args.get("where"), "cid": args.get("cid")})
            elif cat == "dispatch":
                out.append({"kind": "dispatch", "ts": ts,
                            "roots": args.get("roots"), "cid": args.get("cid"),
                            "cids": args.get("cids") or [],
                            "program": args.get("program")})
            elif cat == "collective":
                kind = "fused_collective" if name.startswith("fused:") else "collective"
                op = name[6:] if kind == "fused_collective" else name
                out.append({"kind": kind, "ts": ts, "op": args.get("op", op),
                            "cid": args.get("cid"), "detail": args.get("detail"),
                            "bytes": args.get("bytes"), "count": args.get("count", 1)})
            elif cat == "compile":
                out.append({"kind": "compile", "ts": ts,
                            "program": args.get("program"), "family": args.get("family"),
                            "label": args.get("label"), "cid": args.get("cid")})
            elif cat == "fault":
                out.append({"kind": "fault", "ts": ts, "site": args.get("site")})
            else:
                out.append({"kind": str(cat or "event"), "ts": ts, "name": name})
    dropped = 0
    other = doc.get("otherData")
    if isinstance(other, dict):
        d = _finite(other.get("events_dropped"))
        dropped = int(d) if d else 0
    return hosts, dropped


def _normalize(source) -> Tuple[Dict[int, List[dict]], int, str]:
    """``(hosts, events_dropped, source_kind)`` from any accepted input:
    None (live telemetry state), a raw event list, a Chrome trace document,
    or a path to an exported/merged trace file."""
    if source is None:
        from . import telemetry

        evs = telemetry.events()
        dropped = telemetry._cur().events_dropped
        return ({0: evs} if evs else {}), dropped, "live"
    if isinstance(source, str):
        try:
            with open(source) as fh:
                doc = json.load(fh)
        except OSError as exc:
            raise ValueError(f"cannot read trace {source!r}: {exc}") from exc
        except json.JSONDecodeError as exc:
            raise ValueError(f"{source!r} is not valid JSON: {exc}") from exc
        hosts, dropped = _coerce_doc(doc, source)
        return hosts, dropped, source
    hosts, dropped = _coerce_doc(source, "<doc>")
    return hosts, dropped, "doc"


def _coerce_doc(doc, label: str) -> Tuple[Dict[int, List[dict]], int]:
    if isinstance(doc, list):  # a raw timeline (telemetry.events() shape)
        return ({0: [e for e in doc if isinstance(e, dict)]} if doc else {}), 0
    if isinstance(doc, dict) and isinstance(doc.get("traceEvents"), list):
        return _from_perfetto(doc)
    raise ValueError(
        f"{label}: not a trace — expected a raw event list or a Chrome "
        "trace document with 'traceEvents'"
    )


# ----------------------------------------------------------------------
# per-host attribution: priority interval sweep + explicit remainder
# ----------------------------------------------------------------------
def _join_events(evs: List[dict]):
    """The cid joins the attribution sits on: ``(dispatches, syncs,
    compile_iv, pairs)`` where ``pairs[id(sync)]`` is the dispatch whose
    root set contains the sync's cid, and ``compile_iv`` maps ``id(dispatch)``
    to its cid-joined compile start."""
    dispatches = [e for e in evs if e.get("kind") == "dispatch" and _finite(e.get("ts")) is not None]
    syncs = [e for e in evs if e.get("kind") == "blocking_sync" and _finite(e.get("ts")) is not None]
    by_cid: Dict[Any, dict] = {}
    for d in dispatches:
        cids = d.get("cids") or ([d["cid"]] if d.get("cid") is not None else [])
        for cid in cids:
            by_cid[cid] = d  # last dispatch wins, matching telemetry.async_pairs
    pairs: Dict[int, dict] = {}
    for s in syncs:
        d = by_cid.get(s.get("cid"))
        if d is not None:
            pairs[id(s)] = d
    compile_iv: Dict[int, float] = {}
    for c in evs:
        if c.get("kind") != "compile" or c.get("cid") is None:
            continue
        cts = _finite(c.get("ts"))
        if cts is None:
            continue
        best = None
        for d in dispatches:
            cids = d.get("cids") or ([d["cid"]] if d.get("cid") is not None else [])
            if c["cid"] in cids and d["ts"] >= cts and (best is None or d["ts"] < best["ts"]):
                best = d
        if best is not None:
            prev = compile_iv.get(id(best))
            compile_iv[id(best)] = cts if prev is None else min(prev, cts)
    return dispatches, syncs, compile_iv, pairs


def _attribute_host(evs: List[dict]) -> Dict[str, Any]:
    """One host's attribution: bucket seconds, labeled segments, per-program
    totals, per-chain dispatch/sync counts, and the window bounds."""
    stamps = [
        t for e in evs for t in (_finite(e.get("ts")),) if t is not None
    ]
    if not stamps:
        return {"window": (0.0, 0.0), "buckets": {}, "segments": [],
                "per_program": {}, "chains": [], "unattributed_s": 0.0}
    dispatches, syncs, compile_iv, pairs = _join_events(evs)
    w0 = min(stamps)
    w1 = max(stamps)
    for s in syncs:
        dur = _finite(s.get("dur"))
        if dur is not None and dur >= 0:
            w1 = max(w1, s["ts"] + dur)

    # labeled candidate intervals: (start, end, bucket, program, cid)
    intervals: List[Tuple[float, float, str, Optional[str], Any]] = []

    def add(a, b, bucket, program=None, cid=None):
        a, b = max(a, w0), min(b, w1)
        if b > a:
            intervals.append((a, b, bucket, program, cid))

    # dispatch in-flight spans: dispatch -> last joined sync end; a dispatch
    # with no joined sync keeps the device "not provably idle" to window end
    inflight: Dict[int, float] = {}
    for s in syncs:
        d = pairs.get(id(s))
        if d is None:
            continue
        dur = _finite(s.get("dur")) or 0.0
        end = s["ts"] + max(dur, 0.0)
        inflight[id(d)] = max(inflight.get(id(d), d["ts"]), end)
    for d in dispatches:
        end = inflight.get(id(d), w1)
        add(d["ts"], end, "host_async", d.get("program"), d.get("cid"))

    # compile: cid-joined [compile.ts -> dispatch.ts]
    for d in dispatches:
        cts = compile_iv.get(id(d))
        if cts is not None:
            add(cts, d["ts"], "compile", d.get("program"), d.get("cid"))

    # blocking syncs: split at the joined dispatch stamp
    for s in syncs:
        dur = _finite(s.get("dur"))
        if dur is None or dur < 0:
            continue  # unstamped sync: zero-width, nothing to attribute
        s0, s1 = s["ts"], s["ts"] + dur
        d = pairs.get(id(s))
        where = s.get("where")
        if where == "collective":
            add(s0, s1, "collective", None if d is None else d.get("program"), s.get("cid"))
        elif d is None:
            add(s0, s1, "sync_wait", None, s.get("cid"))
        else:
            split = min(max(d["ts"], s0), s1)
            add(s0, split, "dispatch_queue", d.get("program"), s.get("cid"))
            add(split, s1, "device_execute", d.get("program"), s.get("cid"))

    # priority sweep: every elementary segment takes its highest-priority
    # active label; uncovered segments are host_gap (device provably idle)
    bounds = sorted({w0, w1, *(p for iv in intervals for p in iv[:2])})
    segments: List[dict] = []
    buckets: Dict[str, float] = {}
    for a, b in zip(bounds, bounds[1:]):
        if b <= a:
            continue
        mid = (a + b) / 2.0
        best = ("host_gap", None, None)
        best_p = _BUCKET_PRIORITY["host_gap"]
        for s0, s1, bucket, program, cid in intervals:
            if s0 <= mid < s1 and _BUCKET_PRIORITY[bucket] > best_p:
                best = (bucket, program, cid)
                best_p = _BUCKET_PRIORITY[bucket]
        bucket, program, cid = best
        buckets[bucket] = buckets.get(bucket, 0.0) + (b - a)
        if segments and segments[-1]["bucket"] == bucket \
                and segments[-1]["program"] == program and segments[-1]["cid"] == cid \
                and abs(segments[-1]["end"] - a) < 1e-12:
            segments[-1]["end"] = b
        else:
            segments.append({"start": a, "end": b, "bucket": bucket,
                             "program": program, "cid": cid})

    window_s = w1 - w0
    unattributed = max(0.0, window_s - sum(buckets.values()))

    per_program: Dict[str, Dict[str, Any]] = {}
    for seg in segments:
        if seg["program"] is None or seg["bucket"] not in _BLOCKING_BUCKETS:
            continue
        rec = per_program.setdefault(
            str(seg["program"]),
            {b: 0.0 for b in _BLOCKING_BUCKETS} | {"dispatches": 0, "syncs": 0},
        )
        rec[seg["bucket"]] += seg["end"] - seg["start"]
    for d in dispatches:
        if d.get("program") is not None:
            rec = per_program.setdefault(
                str(d["program"]),
                {b: 0.0 for b in _BLOCKING_BUCKETS} | {"dispatches": 0, "syncs": 0},
            )
            rec["dispatches"] += 1
    for s in syncs:
        d = pairs.get(id(s))
        if d is not None and d.get("program") is not None:
            per_program[str(d["program"])]["syncs"] += 1

    chains = []
    for d in dispatches:
        joined = [s for s in syncs if pairs.get(id(s)) is d]
        chains.append({
            "cid": d.get("cid"),
            "program": d.get("program"),
            "roots": d.get("roots"),
            "dispatches": 1,
            "syncs": len(joined),
            "compiled": id(d) in compile_iv,
        })

    return {"window": (w0, w1), "buckets": buckets, "segments": segments,
            "per_program": per_program, "chains": chains,
            "unattributed_s": unattributed}


# ----------------------------------------------------------------------
# critical path: longest serialized chain of blocking segments
# ----------------------------------------------------------------------
def _critical_path(segments: List[dict]) -> Dict[str, Any]:
    """Longest-duration chain of non-overlapping blocking segments, by
    dynamic programming over end-sorted segments. On a single-threaded host
    the blocking segments are already serial, so this degenerates to "all of
    them" — the DP guards the merged/adversarial cases where reconstructed
    intervals overlap."""
    blocking = [s for s in segments if s["bucket"] in _BLOCKING_BUCKETS]
    blocking.sort(key=lambda s: (s["end"], s["start"]))
    n = len(blocking)
    if not n:
        return {"total_s": 0.0, "sync_pct": 0.0, "steps": [], "truncated": 0}
    ends = [s["end"] for s in blocking]
    best = [0.0] * n
    prev = [-1] * n
    # prefix maxima over best[0..i]: segments that fit before seg i form a
    # PREFIX of the end-sorted order, so the best predecessor is one lookup
    pref_best = [0.0] * n
    pref_arg = [0] * n
    for i, seg in enumerate(blocking):
        dur = seg["end"] - seg["start"]
        best[i] = dur
        j = bisect.bisect_right(ends, seg["start"] + 1e-9, hi=i) - 1
        if j >= 0 and pref_best[j] > 0.0:
            best[i] = pref_best[j] + dur
            prev[i] = pref_arg[j]
        if i == 0 or best[i] > pref_best[i - 1]:
            pref_best[i] = best[i]
            pref_arg[i] = i
        else:
            pref_best[i] = pref_best[i - 1]
            pref_arg[i] = pref_arg[i - 1]
    i = max(range(n), key=lambda k: best[k])
    path = []
    while i >= 0:
        path.append(blocking[i])
        i = prev[i]
    path.reverse()
    total = sum(s["end"] - s["start"] for s in path)
    synced = sum(
        s["end"] - s["start"] for s in path
        if s["bucket"] in ("device_execute", "collective", "sync_wait")
    )
    steps = [
        {
            "bucket": s["bucket"],
            "dur_s": round(s["end"] - s["start"], 6),
            "program": s["program"],
            "cid": s["cid"],
        }
        for s in path
    ]
    truncated = max(0, len(steps) - _MAX_PATH_STEPS)
    if truncated:
        steps = sorted(steps, key=lambda s: -s["dur_s"])[:_MAX_PATH_STEPS]
    return {
        "total_s": round(total, 6),
        "sync_pct": round(100.0 * synced / total, 2) if total > 0 else 0.0,
        "steps": steps,
        "truncated": truncated,
    }


# ----------------------------------------------------------------------
# cross-host straggler / clock-skew attribution
# ----------------------------------------------------------------------
def _median(xs: List[float]) -> float:
    xs = sorted(xs)
    n = len(xs)
    return xs[n // 2] if n % 2 else (xs[n // 2 - 1] + xs[n // 2]) / 2.0


def _stragglers(hosts: Dict[int, List[dict]], straggler_s: float) -> Dict[str, Any]:
    """Per-host clock offset + arrival skew from matched collective events.

    Matching is per (event kind, op, occurrence index) — the k-th allreduce
    on host A pairs with the k-th on host B. Occurrence matching (rather
    than the parity checker's per-cid keys) survives cid drift between
    independently-recorded hosts; under SPMD every host records the same
    collective sequence, so occurrence IS identity. The clock offset is the
    median arrival delta over the EARLIEST quarter of matched keys (a
    straggler's lag accumulates, so late keys would contaminate the offset);
    the residual per-key lag after offset correction names the straggler."""
    arrivals: Dict[int, Dict[tuple, float]] = {}
    for pid, evs in hosts.items():
        seen: Dict[tuple, int] = {}
        table: Dict[tuple, float] = {}
        for ev in evs:
            if ev.get("kind") not in ("collective", "fused_collective"):
                continue
            ts = _finite(ev.get("ts"))
            if ts is None:
                continue
            base = (ev["kind"], str(ev.get("op")))
            k = seen.get(base, 0)
            seen[base] = k + 1
            table[base + (k,)] = ts
        arrivals[pid] = table
    pids = sorted(arrivals)
    doc: Dict[str, Any] = {
        "hosts": len(pids), "matched_collectives": 0,
        "offsets_ms": {}, "lag_ms": {}, "straggler": None, "max_skew_ms": 0.0,
    }
    if len(pids) < 2:
        return doc
    shared = set(arrivals[pids[0]])
    for pid in pids[1:]:
        shared &= set(arrivals[pid])
    if len(shared) < _MIN_MATCHED_COLLECTIVES:
        return doc
    ref = pids[0]
    keys = sorted(shared, key=lambda k: arrivals[ref][k])
    early = keys[: max(_MIN_MATCHED_COLLECTIVES, len(keys) // 4)]
    offsets = {
        pid: _median([arrivals[pid][k] - arrivals[ref][k] for k in early])
        for pid in pids
    }
    lag: Dict[int, float] = {pid: 0.0 for pid in pids}
    max_skew = 0.0
    for k in keys:
        corrected = {pid: arrivals[pid][k] - offsets[pid] for pid in pids}
        first = min(corrected.values())
        last = max(corrected.values())
        max_skew = max(max_skew, last - first)
        for pid in pids:
            lag[pid] = max(lag[pid], corrected[pid] - first)
    worst = max(pids, key=lambda p: lag[p])
    doc.update(
        matched_collectives=len(keys),
        offsets_ms={str(p): round(offsets[p] * 1e3, 3) for p in pids},
        lag_ms={str(p): round(lag[p] * 1e3, 3) for p in pids},
        max_skew_ms=round(max_skew * 1e3, 3),
    )
    if lag[worst] >= straggler_s:
        doc["straggler"] = worst
    return doc


# ----------------------------------------------------------------------
# anti-pattern detectors
# ----------------------------------------------------------------------
def _detect(hosts, per_host, straggle, params) -> List[Finding]:
    findings: List[Finding] = []
    for pid in sorted(hosts):
        evs = hosts[pid]
        ana = per_host[pid]
        findings.extend(_detect_sync_storm(pid, evs, params))
        findings.extend(_detect_retrace_storm(pid, evs, params))
        findings.extend(_detect_reshard_pingpong(pid, evs))
        findings.extend(_detect_idle_gaps(pid, ana, params))
        findings.extend(_detect_numeric(pid, evs))
    if straggle.get("straggler") is not None:
        pid = straggle["straggler"]
        findings.append(Finding(
            rule="tracelens.straggler",
            severity="warning",
            message=(
                f"host {pid} trails its peers by up to "
                f"{straggle['lag_ms'][str(pid)]:g}ms at matched collectives "
                f"({straggle['matched_collectives']} matched, clock offsets "
                "removed) — every collective waits for the slowest arrival"
            ),
            hint="profile host {} alone: look for input-pipeline stalls, cpu "
                 "contention, or thermal throttling on that worker".format(pid),
            host=pid,
            data={"lag_ms": straggle["lag_ms"], "offsets_ms": straggle["offsets_ms"]},
        ))
    return findings


def _detect_sync_storm(pid, evs, params) -> List[Finding]:
    """>K blocking syncs inside one span instance (or any rolling window
    when no spans bound the loop) — the runtime twin of heat-lint H002."""
    k = params["sync_storm_k"]
    syncs = sorted(
        (e["ts"] for e in evs
         if e.get("kind") == "blocking_sync" and _finite(e.get("ts")) is not None),
    )
    findings = []
    # span instances: begin/end pairs per name, a stack per name
    stacks: Dict[str, List[float]] = {}
    spans: List[Tuple[str, float, float]] = []
    for e in sorted(evs, key=lambda e: _finite(e.get("ts")) or 0.0):
        if e.get("kind") == "span_begin":
            stacks.setdefault(str(e.get("name")), []).append(e["ts"])
        elif e.get("kind") == "span_end":
            stack = stacks.get(str(e.get("name")))
            if stack:
                spans.append((str(e.get("name")), stack.pop(), e["ts"]))
    flagged = False
    for name, a, b in spans:
        inside = sum(1 for t in syncs if a <= t <= b)
        if inside > k:
            flagged = True
            findings.append(Finding(
                rule="tracelens.sync_storm", severity="warning",
                message=f"{inside} blocking syncs inside one '{name}' span "
                        f"(threshold {k}) on host {pid} — the host serializes "
                        "on the device once per iteration",
                hint="batch the reads: keep values deferred across the loop "
                     "and read once after it, or use ht.tracelens to confirm "
                     "which boundary forces",
                host=pid, data={"span": name, "syncs": inside},
            ))
    if not flagged and len(syncs) > k:
        # no span bounds the loop: a rolling time window catches the storm
        w = params["sync_storm_window_s"]
        lo = 0
        for hi in range(len(syncs)):
            while syncs[hi] - syncs[lo] > w:
                lo += 1
            if hi - lo + 1 > k:
                findings.append(Finding(
                    rule="tracelens.sync_storm", severity="warning",
                    message=f"{hi - lo + 1} blocking syncs within {w:g}s on "
                            f"host {pid} (threshold {k}) — per-element reads "
                            "are forcing chain after chain",
                    hint="hoist reads out of the loop or read whole arrays "
                         "(.numpy()) instead of items",
                    host=pid, data={"syncs": hi - lo + 1, "window_s": w},
                ))
                break
    return findings


def _detect_retrace_storm(pid, evs, params) -> List[Finding]:
    """One op family paying compile after compile inside the window —
    shape churn defeating the program cache, seen from the trace side."""
    counts: Dict[str, int] = {}
    for e in evs:
        if e.get("kind") != "compile":
            continue
        fam = str(e.get("family") or e.get("label") or e.get("program") or "?")
        counts[fam] = counts.get(fam, 0) + 1
    return [
        Finding(
            rule="tracelens.retrace_storm", severity="warning",
            message=f"op family {fam} compiled {n} times inside the analyzed "
                    f"window on host {pid} — shape churn is defeating the "
                    "program cache",
            hint="pad or bucket the varying dimension (see RetraceWarning); "
                 "every miss pays a fresh XLA compile",
            host=pid, data={"family": fam, "compiles": n},
        )
        for fam, n in sorted(counts.items())
        if n > params["retrace_k"]
    ]


def _detect_reshard_pingpong(pid, evs) -> List[Finding]:
    """Alternating A→B→A reshards in one cid lineage: bytes moved twice to
    end where they started. The fusion layer stamps the target split as the
    reshard node's ``detail``."""
    findings = []
    trail: List[Tuple[Any, Any]] = []  # (cid, target-detail), in ts order
    for e in sorted(
        (e for e in evs if e.get("kind") == "fused_collective"
         and str(e.get("op", "")).startswith("reshard")),
        key=lambda e: _finite(e.get("ts")) or 0.0,
    ):
        trail.append((e.get("cid"), e.get("detail")))
    for i in range(len(trail) - 2):
        (c0, d0), (c1, d1), (c2, d2) = trail[i], trail[i + 1], trail[i + 2]
        if d0 is None or d1 is None:
            continue
        if d0 == d2 and d0 != d1:
            findings.append(Finding(
                rule="tracelens.reshard_pingpong", severity="warning",
                message=f"reshard ping-pong on host {pid}: split {d0} -> {d1} "
                        f"-> {d0} across cids {c0}/{c1}/{c2} — the second hop "
                        "undoes the first",
                hint="keep the intermediate computation on the first layout, "
                     "or fuse the op between the reshards so XLA plans one "
                     "collective",
                host=pid, data={"targets": [d0, d1, d2], "cids": [c0, c1, c2]},
            ))
            break  # one finding per host; the trail names the first instance
    return findings


#: drift above this many ULPs in a ``numeric`` drift event becomes a
#: finding — matches core/numlens.py's default HEAT_TPU_NUMLENS_MAX_ULP
_NUMERIC_DRIFT_ULP = 16


def _detect_numeric(pid, evs) -> List[Finding]:
    """Numerics-lens events on the timeline (``core/numlens.py``,
    HEAT_TPU_NUMLENS): an ``sdc`` canary mismatch is always an error — the
    named device returned wrong bits; a shadow-replay ``drift`` event past
    the ULP threshold is a warning. Plain ``stats`` samples never produce
    findings (a clean instrumented workload stays finding-free)."""
    findings: List[Finding] = []
    sick: Dict[str, int] = {}
    worst_drift = None
    for e in evs:
        if e.get("kind") != "numeric":
            continue
        what = e.get("event")
        if what == "sdc":
            dev = str(e.get("device"))
            sick[dev] = sick.get(dev, 0) + 1
        elif what == "drift":
            ulp = e.get("max_ulp") or 0
            if ulp > _NUMERIC_DRIFT_ULP and (
                worst_drift is None or ulp > worst_drift.get("max_ulp", 0)
            ):
                worst_drift = dict(e)
    for dev, n in sorted(sick.items()):
        findings.append(Finding(
            rule="tracelens.sdc",
            severity="error",
            message=f"SDC sentinel flagged device {dev} on host {pid} "
                    f"{n} time(s): the determinism canary returned wrong "
                    "bits — silent data corruption, not a software bug",
            hint="quarantine the device (resilience.note_device_fault has "
                 "already been fed; three strikes shrink the mesh) and "
                 "re-run the canary after a swap",
            host=pid,
            data={"device": dev, "hits": n},
        ))
    if worst_drift is not None:
        findings.append(Finding(
            rule="tracelens.numeric_drift",
            severity="warning",
            message=f"fused program {worst_drift.get('program')} drifted "
                    f"{worst_drift.get('max_ulp')} ULP from its bitwise "
                    f"eager replay on host {pid} — the fused reorder left "
                    "float tolerance",
            hint="inspect the op family ({}); consider HEAT_TPU_FUSION=0 "
                 "for this chain or widen the accumulation dtype".format(
                     worst_drift.get("family")),
            host=pid,
            data={"program": worst_drift.get("program"),
                  "max_ulp": worst_drift.get("max_ulp")},
        ))
    return findings


def _detect_idle_gaps(pid, ana, params) -> List[Finding]:
    """host_gap segments: the device is provably idle (nothing in flight)
    while the host computes — dead time a pipeline would fill."""
    gap_s = params["idle_gap_ms"] / 1e3
    w0, w1 = ana["window"]
    window = max(w1 - w0, 1e-12)
    gaps = [s for s in ana["segments"]
            if s["bucket"] == "host_gap" and s["end"] - s["start"] >= gap_s]
    if not gaps:
        return []
    total = ana["buckets"].get("host_gap", 0.0)
    pct = 100.0 * total / window
    worst = max(gaps, key=lambda s: s["end"] - s["start"])
    return [Finding(
        rule="tracelens.device_idle",
        severity="warning" if pct >= params["idle_gap_pct"] else "info",
        message=f"device idle {pct:.1f}% of the window on host {pid} "
                f"({len(gaps)} gap(s) >= {params['idle_gap_ms']:g}ms, worst "
                f"{(worst['end'] - worst['start']) * 1e3:.1f}ms) — no dispatch "
                "in flight while the host runs",
        hint="overlap host work with device work: dispatch before the python "
             "section, or pipeline input preparation",
        host=pid,
        data={"gaps": len(gaps), "host_gap_pct": round(pct, 2),
              "worst_ms": round((worst["end"] - worst["start"]) * 1e3, 3)},
    )]


# ----------------------------------------------------------------------
# the public entry points
# ----------------------------------------------------------------------
def analyze(
    source=None,
    *,
    allow_partial: bool = False,
    sync_storm_k: Optional[int] = None,
    retrace_k: Optional[int] = None,
    idle_gap_ms: Optional[float] = None,
    idle_gap_pct: Optional[float] = None,
    straggler_ms: Optional[float] = None,
    sync_storm_window_s: Optional[float] = None,
) -> Dict[str, Any]:
    """Analyze a trace window into the four-part diagnosis.

    ``source``: None (live ``telemetry`` state — requires
    ``HEAT_TPU_TELEMETRY=verbose`` to have been recording), a raw event list
    (``telemetry.events()`` / the flight ring), a Chrome trace document, or
    a path to an ``export_trace``/``merge_traces`` file.

    Refuses a window with dropped events (:class:`TraceIncompleteError`)
    unless ``allow_partial=True`` — attribution over a truncated window
    would silently lie; partial analyses carry ``partial: true`` and a
    ``tracelens.partial`` finding. Pure post-hoc: never forces a chain,
    never initializes a backend."""
    params = {
        "sync_storm_k": _SYNC_STORM_K if sync_storm_k is None else int(sync_storm_k),
        "retrace_k": _RETRACE_STORM_K if retrace_k is None else int(retrace_k),
        "idle_gap_ms": _IDLE_GAP_MS if idle_gap_ms is None else float(idle_gap_ms),
        "idle_gap_pct": _IDLE_GAP_PCT if idle_gap_pct is None else float(idle_gap_pct),
        "sync_storm_window_s": (
            _SYNC_STORM_WINDOW_S if sync_storm_window_s is None else float(sync_storm_window_s)
        ),
    }
    straggler_s = (_STRAGGLER_MS if straggler_ms is None else float(straggler_ms)) / 1e3
    hosts, dropped, src = _normalize(source)
    if not hosts:
        raise ValueError(
            "no events to analyze — record with HEAT_TPU_TELEMETRY=verbose "
            "and export_trace(), or pass a trace file"
        )
    if dropped > 0 and not allow_partial:
        raise TraceIncompleteError(
            f"{dropped} event(s) were dropped past the timeline cap; the "
            "window is incomplete and attribution over it would lie — raise "
            "HEAT_TPU_TELEMETRY_EVENTS or pass allow_partial=True/"
            "--allow-partial to analyze the surviving suffix anyway"
        )

    per_host = {pid: _attribute_host(evs) for pid, evs in hosts.items()}
    window_total = sum(
        max(ana["window"][1] - ana["window"][0], 0.0) for ana in per_host.values()
    )
    overall: Dict[str, float] = {}
    unattributed = 0.0
    for ana in per_host.values():
        unattributed += ana["unattributed_s"]
        for bucket, secs in ana["buckets"].items():
            overall[bucket] = overall.get(bucket, 0.0) + secs

    def _pct(s: float) -> float:
        return round(100.0 * s / window_total, 3) if window_total > 0 else 0.0

    per_program: Dict[str, Dict[str, Any]] = {}
    for ana in per_host.values():
        for key, rec in ana["per_program"].items():
            dst = per_program.setdefault(
                key, {b: 0.0 for b in _BLOCKING_BUCKETS} | {"dispatches": 0, "syncs": 0}
            )
            for b in _BLOCKING_BUCKETS:
                dst[b] = round(dst[b] + rec[b], 6)
            dst["dispatches"] += rec["dispatches"]
            dst["syncs"] += rec["syncs"]

    # critical path: the longest chain among hosts (each host is serial; the
    # slowest host's serialized chain bounds the job)
    paths = {pid: _critical_path(ana["segments"]) for pid, ana in per_host.items()}
    crit_pid = max(paths, key=lambda p: paths[p]["total_s"]) if paths else 0
    critical = dict(paths[crit_pid], host=crit_pid)

    straggle = _stragglers(hosts, straggler_s)
    findings = _detect(hosts, per_host, straggle, params)
    if dropped > 0:
        findings.insert(0, Finding(
            rule="tracelens.partial", severity="info",
            message=f"analysis over a TRUNCATED window: {dropped} event(s) "
                    "dropped past the timeline cap — buckets undercount "
                    "anything that happened before the surviving suffix",
            hint="raise HEAT_TPU_TELEMETRY_EVENTS (or the flight ring cap) "
                 "and re-record",
        ))
    sev_rank = {"error": 0, "warning": 1, "info": 2}
    findings.sort(key=lambda f: (sev_rank.get(f.severity, 3), f.rule))

    chains = [c for ana in per_host.values() for c in ana["chains"]]
    return {
        "schema": SCHEMA,
        "source": src,
        "partial": dropped > 0,
        "events_dropped": dropped,
        "hosts": len(hosts),
        "events": sum(len(evs) for evs in hosts.values()),
        "window_s": round(window_total, 6),
        "attribution": {
            "overall": {
                b: {"s": round(s, 6), "pct": _pct(s)} for b, s in sorted(overall.items())
            },
            "per_host": {
                str(pid): {
                    "window_s": round(ana["window"][1] - ana["window"][0], 6),
                    "buckets": {b: round(s, 6) for b, s in sorted(ana["buckets"].items())},
                    "unattributed_s": round(ana["unattributed_s"], 6),
                }
                for pid, ana in sorted(per_host.items())
            },
            "per_program": per_program,
            "unattributed_s": round(unattributed, 6),
            "unattributed_pct": _pct(unattributed),
        },
        "critical_path": critical,
        "chains": chains,
        "stragglers": straggle,
        "findings": [f.as_dict() for f in findings],
    }


def load_analysis(path: str) -> Dict[str, Any]:
    """An analysis document from disk: a saved :func:`analyze` output is
    returned as-is, a trace file is analyzed first (``allow_partial`` — the
    baseline side of a diff tolerates truncation)."""
    with open(path) as fh:
        doc = json.load(fh)
    if isinstance(doc, dict) and "attribution" in doc and "findings" in doc:
        return doc
    return analyze(doc if not isinstance(doc, dict) or "traceEvents" in doc else doc,
                   allow_partial=True)


# ----------------------------------------------------------------------
# diff: bucket shifts, new findings, critical-path growth
# ----------------------------------------------------------------------
#: regression thresholds for ``analyze --against``
_DIFF_UNATTRIBUTED_PTS = 2.0   # unattributed share may grow this much (pts)
_DIFF_PATH_GROWTH_PCT = 50.0   # critical-path growth that counts as regression


def diff(old: Dict[str, Any], new: Dict[str, Any]) -> Dict[str, Any]:
    """Compare two analyses: per-bucket percentage-point shifts, findings
    that appeared, and critical-path growth. ``regressions`` is the
    CLI-gating list — new warning/error findings, an unattributed share that
    grew past {u} points, or a critical path that grew past {p}%.""".format(
        u=_DIFF_UNATTRIBUTED_PTS, p=_DIFF_PATH_GROWTH_PCT
    )
    shifts: Dict[str, float] = {}
    oa = (old.get("attribution") or {}).get("overall") or {}
    na = (new.get("attribution") or {}).get("overall") or {}
    for bucket in sorted(set(oa) | set(na)):
        d = (na.get(bucket, {}).get("pct", 0.0) or 0.0) - (oa.get(bucket, {}).get("pct", 0.0) or 0.0)
        if abs(d) >= 0.01:
            shifts[bucket] = round(d, 3)
    old_keys = {(f.get("rule"), f.get("host")) for f in old.get("findings", [])}
    new_findings = [
        f for f in new.get("findings", [])
        if (f.get("rule"), f.get("host")) not in old_keys
    ]
    regressions: List[str] = []
    for f in new_findings:
        if f.get("severity") in ("error", "warning"):
            regressions.append(f"new {f['severity']} finding: {f['rule']} — {f['message']}")
    ou = (old.get("attribution") or {}).get("unattributed_pct", 0.0) or 0.0
    nu = (new.get("attribution") or {}).get("unattributed_pct", 0.0) or 0.0
    if nu - ou > _DIFF_UNATTRIBUTED_PTS:
        regressions.append(
            f"unattributed time grew {ou:g}% -> {nu:g}% "
            f"(> {_DIFF_UNATTRIBUTED_PTS:g} points): the accounting lost coverage"
        )
    op = (old.get("critical_path") or {}).get("total_s", 0.0) or 0.0
    np_ = (new.get("critical_path") or {}).get("total_s", 0.0) or 0.0
    growth = (100.0 * (np_ - op) / op) if op > 0 else 0.0
    if op > 0 and growth > _DIFF_PATH_GROWTH_PCT:
        regressions.append(
            f"critical path grew {op:g}s -> {np_:g}s (+{growth:.0f}%, "
            f"> {_DIFF_PATH_GROWTH_PCT:g}%)"
        )
    return {
        "bucket_shifts_pts": shifts,
        "new_findings": new_findings,
        "critical_path_growth_pct": round(growth, 2),
        "regressions": regressions,
        "ok": not regressions,
    }


# ----------------------------------------------------------------------
# rendering: the one-page diagnosis
# ----------------------------------------------------------------------
def render(analysis: Dict[str, Any]) -> str:
    """The one-page human diagnosis of an :func:`analyze` result — the text
    the CLI prints and flight-recorder bundles embed."""
    lines: List[str] = []
    att = analysis.get("attribution") or {}
    window = analysis.get("window_s", 0.0)
    head = (
        f"trace window: {window * 1e3:.1f}ms over {analysis.get('hosts', 0)} host(s), "
        f"{analysis.get('events', 0)} events"
    )
    if analysis.get("partial"):
        head += f"  [PARTIAL: {analysis.get('events_dropped')} events dropped]"
    lines.append(head)
    lines.append("time attribution:")
    overall = att.get("overall") or {}
    for bucket, rec in sorted(overall.items(), key=lambda kv: -kv[1].get("s", 0.0)):
        lines.append(f"  {bucket:<16} {rec.get('s', 0.0) * 1e3:9.2f}ms  {rec.get('pct', 0.0):6.2f}%")
    lines.append(
        f"  {'unattributed':<16} {att.get('unattributed_s', 0.0) * 1e3:9.2f}ms  "
        f"{att.get('unattributed_pct', 0.0):6.2f}%"
    )
    crit = analysis.get("critical_path") or {}
    lines.append(
        f"critical path (host {crit.get('host', 0)}): {crit.get('total_s', 0.0) * 1e3:.2f}ms, "
        f"{crit.get('sync_pct', 0.0):g}% waiting on the device, "
        f"{len(crit.get('steps') or [])} step(s)"
    )
    for step in (crit.get("steps") or [])[:8]:
        prog = f"  [{step['program']}]" if step.get("program") else ""
        lines.append(
            f"  {step['bucket']:<16} {step['dur_s'] * 1e3:9.2f}ms  cid={step.get('cid')}{prog}"
        )
    per_prog = att.get("per_program") or {}
    if per_prog:
        lines.append("per-program (blocking seconds):")
        ranked = sorted(
            per_prog.items(),
            key=lambda kv: -sum(kv[1].get(b, 0.0) for b in _BLOCKING_BUCKETS),
        )
        for key, rec in ranked[:5]:
            busy = sum(rec.get(b, 0.0) for b in _BLOCKING_BUCKETS)
            lines.append(
                f"  {key:<18} {busy * 1e3:9.2f}ms  x{rec.get('dispatches', 0)} dispatches "
                f"/ {rec.get('syncs', 0)} syncs  (compile {rec.get('compile', 0.0) * 1e3:.1f}ms)"
            )
    strag = analysis.get("stragglers") or {}
    if strag.get("hosts", 0) >= 2:
        who = strag.get("straggler")
        verdict = f"host {who} STRAGGLES" if who is not None else "no straggler"
        lines.append(
            f"cross-host: {verdict} (lag {strag.get('lag_ms')}, offsets "
            f"{strag.get('offsets_ms')}, {strag.get('matched_collectives', 0)} "
            "matched collectives)"
        )
    findings = analysis.get("findings") or []
    if findings:
        lines.append(f"findings ({len(findings)}):")
        for f in findings:
            lines.append(f"  [{f.get('severity', '?'):<7}] {f.get('rule')}: {f.get('message')}")
            if f.get("hint"):
                lines.append(f"            fix: {f['hint']}")
    else:
        lines.append("findings: none — nothing structural bounds this window")
    return "\n".join(lines)


def diagnose(events: List[dict], **kwargs) -> Dict[str, Any]:
    """The flight-recorder one-pager: analyze a raw event ring (always
    ``allow_partial`` — a ring is a window by construction) and return a
    compact ``{"text", "attribution", "critical_path", "findings", ...}``
    block sized for embedding in a forensics bundle. Never raises — a bundle
    must ship even when the ring holds nothing analyzable."""
    try:
        kwargs.setdefault("allow_partial", True)
        analysis = analyze(list(events), **kwargs)
    except Exception as exc:  # noqa: BLE001 - forensics must never fail the dump
        return {"error": repr(exc)}
    crit = dict(analysis["critical_path"])
    crit["steps"] = crit.get("steps", [])[:10]
    return {
        "text": render(analysis),
        "window_s": analysis["window_s"],
        "attribution": analysis["attribution"]["overall"],
        "unattributed_pct": analysis["attribution"]["unattributed_pct"],
        "critical_path": crit,
        "stragglers": analysis["stragglers"],
        "findings": analysis["findings"],
    }

"""Tile decompositions over distributed arrays (reference: heat/core/tiling.py).

The reference builds two tile abstractions on top of per-rank ``torch``
shards: ``SplitTiles`` (one tile per process along every axis, used by the
arbitrary-axis ``resplit``, reference tiling.py:14-330) and
``SquareDiagTiles`` (diagonal-aligned tiles for tile-QR, reference
tiling.py:331-1257).

TPU-native realization: a ``DNDarray`` is a *global* ``jax.Array``; a tile is
a rectangular slice of the global index space, so both classes here are pure
index arithmetic plus global-view slicing. No P2P choreography is needed —
reading a tile that lives on another device is a sharded gather XLA lowers to
the matching ICI collective, and writing one is a functional ``.at[]`` update.
The public surface (properties, ``__getitem__``/``__setitem__``,
``local_get``/``local_set``, ``match_tiles``) mirrors the reference so code
written against it ports over.
"""

from __future__ import annotations

from typing import List, Optional, Tuple, Union

import numpy as np

from .dndarray import DNDarray

__all__ = ["SplitTiles", "SquareDiagTiles"]


def _axis_tile_sizes(length: int, n: int) -> np.ndarray:
    """Block sizes when ``length`` is chunked into ``n`` contiguous blocks
    under GSPMD's ceil-division rule — the layout this runtime actually
    places shards with (communication.py:counts_displs_shape), so tile
    ownership matches physical ownership. (The reference balances the
    remainder across the lowest ranks instead, reference
    communication.py:193-203 — an MPI layout this runtime does not use.)"""
    if n <= 0:
        return np.zeros(0, dtype=np.int64)
    block = -(-length // n) if length else 0
    return np.array(
        [max(0, min(block, length - i * block)) for i in range(n)], dtype=np.int64
    )


class SplitTiles:
    """One tile per device along *every* axis (reference tiling.py:14-136).

    ``tile_dimensions[d]`` holds the tile extents along axis ``d``;
    ``tile_ends_g`` the inclusive global end indices; ``tile_locations`` maps
    each tile to the device that owns it (determined by the split axis alone).
    """

    def __init__(self, arr: DNDarray):
        self.__arr = arr
        n = arr.comm.size
        dims = max(arr.ndim, 1)
        sizes = np.zeros((dims, n), dtype=np.int64)
        for d in range(arr.ndim):
            sizes[d] = _axis_tile_sizes(arr.gshape[d], n)
        self.__tile_dimensions = sizes
        self.__tile_ends_g = np.cumsum(sizes, axis=1) - 1
        self.__tile_locations = self.set_tile_locations(arr.split, sizes, arr)

    @staticmethod
    def set_tile_locations(split: Optional[int], tile_dims: np.ndarray, arr: DNDarray) -> np.ndarray:
        """Device-ownership grid: tiles are owned by the device holding their
        slab of the split axis; replicated arrays live on device 0
        (reference tiling.py:108-135)."""
        n = arr.comm.size
        shape = tuple(tile_dims.shape[1] for _ in range(max(arr.ndim, 1)))
        locs = np.zeros(shape, dtype=np.int64)
        if split is None or arr.ndim == 0:
            return locs
        idx = [None] * arr.ndim
        idx[split] = slice(None)
        locs += np.arange(n, dtype=np.int64)[tuple(idx)]
        return locs

    @property
    def arr(self) -> DNDarray:
        return self.__arr

    @property
    def lshape_map(self) -> np.ndarray:
        return self.__arr.comm.lshape_map(self.__arr.gshape, self.__arr.split)

    @property
    def tile_locations(self) -> np.ndarray:
        return self.__tile_locations

    @property
    def tile_ends_g(self) -> np.ndarray:
        return self.__tile_ends_g

    @property
    def tile_dimensions(self) -> np.ndarray:
        return self.__tile_dimensions

    # ------------------------------------------------------------------
    def __tile_slices(self, key) -> Tuple[slice, ...]:
        """Translate a per-axis tile key into global index slices
        (reference tiling.py:229-281)."""
        if not isinstance(key, tuple):
            key = (key,)
        out = []
        for d in range(self.__arr.ndim):
            k = key[d] if d < len(key) else slice(None)
            starts = np.concatenate(([0], self.__tile_ends_g[d][:-1] + 1))
            ends = self.__tile_ends_g[d] + 1
            if isinstance(k, slice):
                idx = range(*k.indices(len(ends)))
                if len(idx) == 0:
                    out.append(slice(0, 0))
                else:
                    out.append(slice(int(starts[idx[0]]), int(ends[idx[-1]])))
            else:
                k = int(k)
                out.append(slice(int(starts[k]), int(ends[k])))
        return tuple(out)

    def get_tile_size(self, key) -> Tuple[int, ...]:
        """Shape of the tile(s) selected by ``key`` (reference tiling.py:282-330)."""
        return tuple(s.stop - s.start for s in self.__tile_slices(key))

    def __getitem__(self, key):
        return self.__arr.larray[self.__tile_slices(key)]

    def __setitem__(self, key, value) -> None:
        self.__arr.larray = self.__arr.larray.at[self.__tile_slices(key)].set(value)


class SquareDiagTiles:
    """Diagonal-aligned tile decomposition for tile-QR (reference
    tiling.py:331-724).

    Tiles are square along the diagonal: row boundaries equal column
    boundaries up to the diagonal's end, with ``tiles_per_proc`` tiles on
    each device's slab of the split axis. The TPU QR path
    (:mod:`heat_tpu.core.linalg.qr`) uses a TSQR reduction tree instead of
    tile-CAQR, so this class serves the metadata/indexing API.
    """

    def __init__(self, arr: DNDarray, tiles_per_proc: int = 2):
        if not isinstance(tiles_per_proc, int) or tiles_per_proc < 1:
            raise ValueError(f"tiles_per_proc must be a positive int, got {tiles_per_proc}")
        if arr.ndim != 2:
            raise ValueError(f"arr must be 2D, got {arr.ndim}D")
        self.__arr = arr
        n = arr.comm.size
        m, k = arr.gshape
        split = arr.split if arr.split is not None else 0

        # boundaries of the split axis: per-device slabs cut into
        # tiles_per_proc tiles each
        slab_sizes = _axis_tile_sizes(arr.gshape[split], n)
        split_bounds: List[int] = [0]
        for sz in slab_sizes:
            for t in _axis_tile_sizes(int(sz), tiles_per_proc):
                if t > 0:
                    split_bounds.append(split_bounds[-1] + int(t))
        # de-dup (empty slabs) and drop the leading 0
        split_inds = sorted(set(split_bounds))[:-1]

        # the non-split axis mirrors the split boundaries up to the diagonal
        # end, then a single remainder tile (square-diagonal property)
        diag_end = min(m, k)
        other_len = arr.gshape[1 - split]
        other_inds = [b for b in split_inds if b < diag_end and b < other_len]
        if split == 0:
            self.__row_inds, self.__col_inds = list(split_inds), list(other_inds)
        else:
            self.__row_inds, self.__col_inds = list(other_inds), list(split_inds)
        self.__tiles_per_proc = tiles_per_proc
        self.__split = split
        self.__slab_starts = np.cumsum(np.concatenate(([0], slab_sizes)))[:-1]
        self.__rebuild_maps()

    def __rebuild_maps(self) -> None:
        """(Re)derive tile_map and last_diagonal_process from the current
        row/col boundaries — called at construction and after match_tiles."""
        arr, split, n = self.__arr, self.__split, self.__arr.comm.size
        m, k = arr.gshape
        diag_end = min(m, k)

        def owner(start: int) -> int:
            # the device whose split-axis slab contains global index `start`
            return int(np.searchsorted(self.__slab_starts, start, side="right") - 1)

        row_bounds = self.__row_inds + [m]
        col_bounds = self.__col_inds + [k]
        self.__tile_map = np.zeros((len(self.__row_inds), len(self.__col_inds), 3), dtype=np.int64)
        for i in range(len(self.__row_inds)):
            for j in range(len(self.__col_inds)):
                self.__tile_map[i, j, 0] = row_bounds[i]
                self.__tile_map[i, j, 1] = col_bounds[j]
                start = row_bounds[i] if split == 0 else col_bounds[j]
                self.__tile_map[i, j, 2] = owner(start)

        # last device owning part of the diagonal
        self.__last_diag_pr = int(
            np.searchsorted(self.__slab_starts, diag_end - 1, side="right") - 1
        )

    # ------------------------------------------------------------------
    @property
    def arr(self) -> DNDarray:
        return self.__arr

    @property
    def col_indices(self) -> List[int]:
        return list(self.__col_inds)

    @property
    def row_indices(self) -> List[int]:
        return list(self.__row_inds)

    @property
    def lshape_map(self) -> np.ndarray:
        return self.__arr.comm.lshape_map(self.__arr.gshape, self.__arr.split)

    @property
    def last_diagonal_process(self) -> int:
        return self.__last_diag_pr

    @property
    def tile_columns(self) -> int:
        return len(self.__col_inds)

    @property
    def tile_rows(self) -> int:
        return len(self.__row_inds)

    @property
    def tile_columns_per_process(self) -> List[int]:
        counts = np.bincount(self.__tile_map[0, :, 2], minlength=self.__arr.comm.size)
        return [int(c) for c in counts] if self.__arr.split == 1 else [self.tile_columns] * self.__arr.comm.size

    @property
    def tile_rows_per_process(self) -> List[int]:
        counts = np.bincount(self.__tile_map[:, 0, 2], minlength=self.__arr.comm.size)
        return [int(c) for c in counts] if self.__arr.split in (0, None) else [self.tile_rows] * self.__arr.comm.size

    @property
    def tile_map(self) -> np.ndarray:
        return self.__tile_map

    @property
    def tiles_per_proc(self) -> int:
        return self.__tiles_per_proc

    # ------------------------------------------------------------------
    def get_start_stop(self, key) -> Tuple[int, int, int, int]:
        """Global (row_start, row_stop, col_start, col_stop) of the tile(s)
        at ``key`` (reference tiling.py:824-938 returns local offsets; the
        global view needs no rank translation)."""
        rs, cs = self.__key_to_slices(key)
        return rs.start, rs.stop, cs.start, cs.stop

    def __key_to_slices(self, key) -> Tuple[slice, slice]:
        if not isinstance(key, tuple):
            key = (key, slice(None))
        row_bounds = self.__row_inds + [self.__arr.gshape[0]]
        col_bounds = self.__col_inds + [self.__arr.gshape[1]]

        def resolve(k, bounds):
            n = len(bounds) - 1
            if isinstance(k, slice):
                idx = range(*k.indices(n))
                if len(idx) == 0:
                    return slice(0, 0)
                return slice(bounds[idx[0]], bounds[idx[-1] + 1])
            return slice(bounds[int(k)], bounds[int(k) + 1])

        return resolve(key[0], row_bounds), resolve(key[1], col_bounds)

    def __getitem__(self, key):
        rs, cs = self.__key_to_slices(key)
        return self.__arr.larray[rs, cs]

    def __setitem__(self, key, value) -> None:
        rs, cs = self.__key_to_slices(key)
        self.__arr.larray = self.__arr.larray.at[rs, cs].set(value)

    # the reference's local_* operate on the calling rank's shard; with a
    # global array every tile is addressable, so local == global
    def local_get(self, key):
        """(reference tiling.py:939-958)"""
        return self[key]

    def local_set(self, key, value) -> None:
        """(reference tiling.py:959-1021)"""
        self[key] = value

    def local_to_global(self, key, rank: Optional[int] = None):
        """Identity under the global view (reference tiling.py:1022-1083)."""
        return key

    def match_tiles(self, tiles_to_match: "SquareDiagTiles") -> None:
        """Align this decomposition's boundaries with another's so tile keys
        agree between the two arrays (reference tiling.py:1084-1257)."""
        self.__row_inds = [b for b in tiles_to_match.row_indices if b < self.__arr.gshape[0]]
        self.__col_inds = [b for b in tiles_to_match.col_indices if b < self.__arr.gshape[1]]
        self.__rebuild_maps()

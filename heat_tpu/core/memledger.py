"""Memory observability: the live-buffer ledger, high watermark, headroom
admission gate and OOM forensics.

Heat's whole reason to exist is arrays that don't fit one host (the HeAT
paper, arXiv:2007.13552, positions memory capacity — not flops — as the
scaling wall for distributed data analytics), yet until this module the
memory story was two best-effort snapshots folded into
``telemetry.report()["memory"]``. This module makes memory a first-class
observable with four connected surfaces:

* **The live-buffer ledger** (:func:`ledger`) — every ``jax.live_arrays()``
  buffer attributed to an *owner*: ``dndarray`` (payloads stored on
  wrappers, tagged at construction and at the ``parray`` forcing seam),
  ``fusion`` (dispatched-but-unclaimed async futures installed by
  ``fusion.force``), ``checkpoint`` / ``io`` (staging and ingest arrays,
  tagged via :func:`owner_scope`), and ``unattributed`` (foreign arrays the
  user created directly with jax). Attribution rides a weakref registry
  (:func:`tag`) — entries die with their arrays, id-reuse is guarded by
  identity-checking the weakref — and buffers addressable from multiple
  shards are deduped by (device, buffer pointer), so a replicated array
  counts once per device buffer, never once per view.
* **The high watermark** (:func:`watermark`) — the largest live total (and
  its per-owner split) any :func:`sample` has seen. Samples are taken at the
  dispatch/force/collective/checkpoint seams (``telemetry`` calls
  :func:`note` from its record functions; the admission gate samples on
  every check) and are *throttled* (``HEAT_TPU_MEMORY_SAMPLE_MS``, default
  20 ms) so the hot path stays cheap; ``sample(force=True)`` bypasses the
  throttle for tests and benches. In verbose telemetry each sample lands on
  the trace timeline as a ``memory`` event, exported to Perfetto as per-host
  counter ("C") tracks.
* **The headroom admission gate** (:func:`admit`) — ``HEAT_TPU_MEMORY_BUDGET``
  (absolute bytes, with ``KiB``/``MiB``/``GiB`` suffixes, or a 0<x<=1
  fraction of device — falling back to host — memory) is checked at the
  fused-program dispatch seam against *live ledger bytes + the program's
  static peak* (XLA's ``memory_analysis()`` when the cost is memoized,
  operand+result bytes otherwise). ``HEAT_TPU_MEMORY_POLICY`` picks what
  happens on projected overrun: ``warn`` (once per program key), ``raise``
  (:class:`MemoryBudgetExceeded` *before* the dispatch — the chain stays
  pending and can be forced after the budget is lifted), or ``drain``
  (blocking-sync every outstanding async root first, then re-check and warn
  only if still over). This is the direct prework for ROADMAP 4's
  token-bucket admission control, and the gauge that lets ROADMAP 3's
  resplit rewrite assert O(n/p) peak.
* **OOM forensics** (:func:`record_oom` / :func:`last_oom`) — when a fused
  dispatch dies of ``RESOURCE_EXHAUSTED`` / ``XlaRuntimeError`` OOM /
  ``MemoryError`` (injectable at the ``memory.exhausted`` fault site),
  ``fusion.force`` produces a ranked diagnostic — top live buffers by
  owner, the failing program's key and static peak, the last-N dispatches
  from the trace timeline — as a :class:`MemoryExhaustedWarning` *before*
  handing the chain to resilience's guarded degrade path, so the answer to
  "what ate the HBM" survives the recovery.

Everything here is observability: :func:`ledger`/:func:`sample` never force
a pending chain (``jax.live_arrays`` holds only concrete buffers), never
raise, and never initialize a backend (jax is imported lazily; the
``telemetry.report()`` memory block additionally gates on the mesh
singleton). The ledger attribution registry is always on (one dict store
per payload store); sampling hooks obey :func:`set_enabled` /
``HEAT_TPU_MEMORY_LEDGER=0``.
"""

from __future__ import annotations

import os
import time
import warnings
import weakref
from contextlib import contextmanager
from typing import Any, Dict, List, Optional, Tuple

from . import telemetry

__all__ = [
    "MemoryBudgetExceeded",
    "MemoryBudgetWarning",
    "MemoryExhaustedWarning",
    "admission_hold",
    "admit",
    "budget_info",
    "gate_exempt",
    "gate_stats",
    "hold_info",
    "invalidate_resolved_budget",
    "is_oom",
    "last_oom",
    "ledger",
    "note",
    "owner_scope",
    "parse_budget",
    "record_oom",
    "reset",
    "reset_watermark",
    "sample",
    "set_budget",
    "set_enabled",
    "tag",
    "watermark",
]

_OFF_VALUES = ("", "0", "false", "off", "no")


class MemoryBudgetExceeded(MemoryError):
    """A dispatch was refused by the headroom admission gate
    (``HEAT_TPU_MEMORY_POLICY=raise``): projected bytes (live ledger +
    static program peak) exceed ``HEAT_TPU_MEMORY_BUDGET``. Raised *before*
    the program runs — the pending chain is left intact and can be forced
    once the budget is lifted or memory is freed."""


class MemoryBudgetWarning(UserWarning):
    """Projected bytes for a dispatch exceed the memory budget under the
    ``warn`` policy (or still exceed it after a ``drain``). Warned once per
    program key."""


class MemoryExhaustedWarning(UserWarning):
    """A fused dispatch died of device memory exhaustion; the warning
    carries the ranked forensic diagnostic (:func:`last_oom` holds the
    structured form) and the chain degrades to per-op eager replay."""


# ----------------------------------------------------------------------
# owner registry: id(arr) -> (weakref, owner)
# ----------------------------------------------------------------------
#: attribution registry. Keyed by id() with an identity-checked weakref (a
#: recycled id can never inherit a dead array's owner); the weakref death
#: callback removes the entry, so the registry never outlives its arrays.
_REGISTRY: Dict[int, Tuple[Any, str]] = {}

#: ambient owner for arrays tagged without an explicit owner (the
#: checkpoint/io staging seams push scopes; innermost wins)
_OWNER_STACK: List[str] = []

#: default owner bucket for live buffers nobody tagged
UNATTRIBUTED = "unattributed"


def tag(arr, owner: Optional[str] = None) -> None:
    """Attribute ``arr``'s buffers to ``owner`` (or the innermost
    :func:`owner_scope`). The LAST tag wins — a fused async future re-tagged
    at the ``parray`` seam moves from ``fusion`` to ``dndarray``. No-op for
    non-weakref-able values (numpy arrays, scalars, tracers have no device
    buffer to account)."""
    if owner is None:
        owner = _OWNER_STACK[-1] if _OWNER_STACK else UNATTRIBUTED
    key = id(arr)
    try:
        ref = weakref.ref(arr, lambda r, key=key: _drop_entry(key, r))
    except TypeError:  # not weakref-able: nothing device-side to track
        return
    _REGISTRY[key] = (ref, owner)


def _drop_entry(key: int, ref) -> None:
    cur = _REGISTRY.get(key)
    if cur is not None and cur[0] is ref:
        _REGISTRY.pop(key, None)


def _owner_of(arr) -> str:
    rec = _REGISTRY.get(id(arr))
    if rec is not None and rec[0]() is arr:
        return rec[1]
    return UNATTRIBUTED


@contextmanager
def owner_scope(owner: str):
    """Attribute every :func:`tag` without an explicit owner inside this
    scope to ``owner`` — the seam ``utils/checkpoint.py`` (restore staging)
    and ``core/io.py`` (sharded ingest) wrap their array-producing bodies
    in, so transient staging buffers show up under their subsystem instead
    of ``unattributed``. Scopes nest; the innermost wins."""
    _OWNER_STACK.append(str(owner))
    try:
        yield
    finally:
        _OWNER_STACK.pop()


def current_owner() -> Optional[str]:
    """The innermost active :func:`owner_scope`, or None outside any."""
    return _OWNER_STACK[-1] if _OWNER_STACK else None


# ----------------------------------------------------------------------
# the live-buffer walk (shared by ledger / sample / the gate)
# ----------------------------------------------------------------------
def _buffer_key(shard, arr, i):
    """Dedupe key for one addressable shard: (device, buffer pointer) where
    the backend exposes it, else (owning array id, shard index) — a buffer
    addressable from multiple shards/views must count once."""
    try:
        return (str(shard.device), shard.data.unsafe_buffer_pointer())
    except (AttributeError, RuntimeError, ValueError, NotImplementedError):
        return (id(arr), i)


def _scan(top: int = 0) -> Dict[str, Any]:
    """One pass over ``jax.live_arrays()``: total bytes, per-owner bytes,
    deduped buffer count, and (``top`` > 0) the largest buffers. Never
    forces (live arrays are concrete), never raises past jax being absent,
    and skips deleted/donated buffers without a blanket except (the deleted
    race surfaces as ``RuntimeError`` from the shards read)."""
    out: Dict[str, Any] = {"total_bytes": 0, "by_owner": {}, "buffers": 0, "top": []}
    try:
        import jax

        arrays = jax.live_arrays()
    except Exception:  # pragma: no cover - no backend at all
        return out
    by_owner = out["by_owner"]
    seen = set()
    largest: List[Tuple[int, str, tuple, str]] = []
    # attributed arrays claim their buffers FIRST: jax tracks a global
    # sharded array AND its per-shard children as separate live arrays over
    # the same device buffers, so the dedupe pass must let the tagged owner
    # win regardless of live_arrays() enumeration order
    ranked = sorted(arrays, key=lambda arr: _owner_of(arr) == UNATTRIBUTED)
    for arr in ranked:
        try:
            if arr.is_deleted():
                continue
            shards = arr.addressable_shards
        except RuntimeError:  # deleted/donated between the check and the read
            continue
        owner = _owner_of(arr)
        arr_bytes = 0
        for i, s in enumerate(shards):
            key = _buffer_key(s, arr, i)
            if key in seen:
                continue
            seen.add(key)
            try:
                nbytes = int(s.data.nbytes)
            except RuntimeError:  # deleted mid-walk
                continue
            arr_bytes += nbytes
            out["buffers"] += 1
        if not arr_bytes:
            continue
        out["total_bytes"] += arr_bytes
        by_owner[owner] = by_owner.get(owner, 0) + arr_bytes
        if top:
            largest.append(
                (arr_bytes, owner, tuple(int(d) for d in arr.shape), str(arr.dtype))
            )
    if top:
        largest.sort(key=lambda t: -t[0])
        out["top"] = [
            {"nbytes": n, "owner": o, "shape": list(sh), "dtype": dt}
            for n, o, sh, dt in largest[:top]
        ]
    return out


def _scan_total() -> int:
    """Deduped live bytes only — no owner attribution, no sorting, no top-K.
    The admission gate's per-dispatch fast path: the within-budget decision
    needs one number, and the O(n log n) attributed walk would otherwise
    ride every armed dispatch (attribution is computed lazily, only on the
    over-budget path)."""
    try:
        import jax

        arrays = jax.live_arrays()
    except Exception:  # pragma: no cover - no backend at all
        return 0
    seen = set()
    total = 0
    for arr in arrays:
        try:
            if arr.is_deleted():
                continue
            shards = arr.addressable_shards
        except RuntimeError:  # deleted/donated between the check and the read
            continue
        for i, s in enumerate(shards):
            key = _buffer_key(s, arr, i)
            if key in seen:
                continue
            seen.add(key)
            try:
                total += int(s.data.nbytes)
            except RuntimeError:  # deleted mid-walk
                continue
    return total


def ledger(top: int = 5) -> Dict[str, Any]:
    """The owner-attributed live-buffer ledger: ``total_bytes``, per-owner
    ``by_owner`` bytes, the deduped ``buffers`` count and the ``top``-K
    largest buffers (owner/shape/dtype/bytes). Read-only and force-free —
    safe to call with chains pending."""
    return _scan(top=max(0, int(top)))


# ----------------------------------------------------------------------
# sampling + the high watermark
# ----------------------------------------------------------------------
_ENABLED = os.environ.get("HEAT_TPU_MEMORY_LEDGER", "1").strip().lower() not in _OFF_VALUES
_SAMPLE_EVERY_S = max(0.0, float(os.environ.get("HEAT_TPU_MEMORY_SAMPLE_MS", "20"))) / 1e3
_LAST_SAMPLE_TS = 0.0

_WATERMARK: Dict[str, Any] = {"bytes": 0, "by_owner": {}, "event": None, "samples": 0}


def set_enabled(flag: bool) -> bool:
    """Flip the sampling hooks in-process (``HEAT_TPU_MEMORY_LEDGER`` env
    knob at import); returns the previous state. Attribution tagging and the
    on-demand :func:`ledger` stay available either way."""
    global _ENABLED
    prev, _ENABLED = _ENABLED, bool(flag)
    return prev


def sample(event: str = "manual", force: bool = False) -> Optional[Dict[str, Any]]:
    """Take one ledger sample, update the high watermark, and (verbose
    telemetry) emit a ``memory`` timeline event the Perfetto exporter
    renders as counter tracks. Throttled to one sample per
    ``HEAT_TPU_MEMORY_SAMPLE_MS`` unless ``force=True``; returns the
    snapshot taken, or None when throttled/disabled.

    Cost discipline: the hook path (``note`` from the telemetry record
    seams, mode <= 1) pays only the deduped total — the attributed
    sort-walk runs when a new peak must bank its owner split, when the
    caller forced the sample, or in verbose mode (the exported counter
    tracks carry the per-owner series). The telemetry overhead guard
    (enabled dispatch rate >= 0.9x disabled) stays green with the hooks on."""
    global _LAST_SAMPLE_TS
    if not force:
        if not _ENABLED:
            return None
        now = time.perf_counter()
        if now - _LAST_SAMPLE_TS < _SAMPLE_EVERY_S:
            return None
    verbose = telemetry._MODE >= 2
    snap = _scan() if (force or verbose) else None
    total = snap["total_bytes"] if snap is not None else _scan_total()
    _LAST_SAMPLE_TS = time.perf_counter()
    _WATERMARK["samples"] += 1
    if total > _WATERMARK["bytes"]:
        if snap is None:
            snap = _scan()  # a new peak banks its owner split
        _WATERMARK["bytes"] = max(total, snap["total_bytes"])
        _WATERMARK["by_owner"] = dict(snap["by_owner"])
        _WATERMARK["event"] = event
    if verbose and snap is not None:
        telemetry.record_event(
            "memory",
            event=event,
            total=snap["total_bytes"],
            by_owner=dict(snap["by_owner"]),
            watermark=_WATERMARK["bytes"],
        )
    if snap is not None:
        return snap
    return {"total_bytes": total, "by_owner": {}, "buffers": 0, "top": []}


def note(event: str) -> None:
    """The hot-path sampling hook (telemetry's record functions and the
    admission gate call it at the dispatch/force/collective/checkpoint
    seams). One attribute read when disabled; throttled otherwise."""
    if _ENABLED:
        sample(event)


def watermark() -> Dict[str, Any]:
    """The high watermark: the largest sampled live total (``bytes``), its
    per-owner split, the event kind that set it, and how many samples have
    been taken. Pure state — never touches jax."""
    return {
        "bytes": _WATERMARK["bytes"],
        "by_owner": dict(_WATERMARK["by_owner"]),
        "event": _WATERMARK["event"],
        "samples": _WATERMARK["samples"],
    }


def reset_watermark() -> None:
    """Zero the watermark (benches bracket a measured region with this)."""
    _WATERMARK.update(bytes=0, by_owner={}, event=None, samples=0)


# ----------------------------------------------------------------------
# the headroom admission gate
# ----------------------------------------------------------------------
_UNITS = {
    "b": 1,
    "kb": 10**3, "mb": 10**6, "gb": 10**9, "tb": 10**12,
    "kib": 1 << 10, "mib": 1 << 20, "gib": 1 << 30, "tib": 1 << 40,
    # bare single letters read as binary — "2G" means memory, not disk ads
    "k": 1 << 10, "m": 1 << 20, "g": 1 << 30, "t": 1 << 40,
}


def parse_budget(value) -> Optional[object]:
    """Parse a budget spec: ``None``/off-words disarm; an int (or suffixed
    string like ``"512MiB"``) is absolute bytes; a float in (0, 1] is a
    fraction of device (else host) memory, resolved lazily at the first
    gate check. Returns int bytes, float fraction, or None."""
    if value is None:
        return None
    if isinstance(value, bool):
        raise ValueError("memory budget must be bytes or a fraction, not a bool")
    if isinstance(value, (int, float)):
        if isinstance(value, float) and 0.0 < value <= 1.0:
            return float(value)
        if value <= 0:
            return None
        return int(value)
    text = str(value).strip().lower()
    if text in _OFF_VALUES:
        return None
    for unit in sorted(_UNITS, key=len, reverse=True):
        if text.endswith(unit) and text[: -len(unit)].strip():
            return int(float(text[: -len(unit)].strip()) * _UNITS[unit])
    num = float(text)
    if 0.0 < num <= 1.0:
        return num
    if num <= 0:
        return None
    return int(num)


_POLICIES = ("warn", "raise", "drain")

def _parse_env_budget(value) -> Optional[object]:
    """The env-knob form of :func:`parse_budget`: a malformed value warns
    and disarms instead of making ``import heat_tpu`` raise — the same
    typo-must-not-take-the-process-down contract as the policy knob."""
    try:
        return parse_budget(value)
    except (ValueError, TypeError):
        warnings.warn(
            f"HEAT_TPU_MEMORY_BUDGET={value!r} is not parseable (bytes, a "
            "KiB/MiB/GiB-suffixed string, or a 0-1 fraction); the admission "
            "gate stays disarmed",
            stacklevel=1,
        )
        return None


#: the armed budget (int bytes / float fraction / None) — module attribute
#: so the dispatch hot path gates with one attribute read when disarmed
_BUDGET_RAW = _parse_env_budget(os.environ.get("HEAT_TPU_MEMORY_BUDGET"))
_POLICY = os.environ.get("HEAT_TPU_MEMORY_POLICY", "warn").strip().lower() or "warn"
if _POLICY not in _POLICIES:  # a typo'd env knob must not take the process down
    warnings.warn(
        f"HEAT_TPU_MEMORY_POLICY={_POLICY!r} is not one of {_POLICIES}; using 'warn'",
        stacklevel=1,
    )
    _POLICY = "warn"

#: lazily-resolved absolute budget for fractional specs (device memory where
#: the backend exposes bytes_limit, host physical memory otherwise)
_RESOLVED_BUDGET: Optional[int] = None

_GATE_STATS = {
    "checks": 0, "allowed": 0, "exceeded": 0,
    "drains": 0, "drained_roots": 0, "warned": 0, "raised": 0,
    "held": 0,
}
_WARNED_KEYS: set = set()

#: reentrancy guard: a drain forces other pending roots, whose forces must
#: not re-enter the gate (they are the freeing, not new admissions)
_IN_GATE = False

#: non-None = every NEW fused-dispatch admission is refused, naming the
#: holder — the elastic supervisor's "stop admitting" seam during its
#: drain → checkpoint → reform window (reuses this gate rather than adding
#: a second dispatch interlock)
_HOLD: Optional[str] = None


@contextmanager
def admission_hold(reason: str):
    """Refuse every NEW fused-dispatch admission for the scope's duration:
    :func:`admit` raises :class:`MemoryBudgetExceeded` naming ``reason``,
    leaving the refused chain pending (it dispatches after release, exactly
    like the budget ``raise`` policy). Reentrant/drain forces pass — under
    :func:`gate_exempt` or ``_IN_GATE`` they are the draining itself, not
    new work. The elastic supervisor holds admissions while it drains live
    roots and re-forms the mesh so no dispatch races the dying world."""
    global _HOLD
    prev, _HOLD = _HOLD, str(reason)
    try:
        yield
    finally:
        _HOLD = prev


@contextmanager
def gate_exempt():
    """Run with the admission gate held open (``_IN_GATE`` semantics): every
    :func:`admit` inside returns immediately. The elastic supervisor wraps
    its drain/commit/restore in this — those forces ARE the drain."""
    global _IN_GATE
    prev, _IN_GATE = _IN_GATE, True
    try:
        yield
    finally:
        _IN_GATE = prev


def hold_info() -> Optional[str]:
    """The active admission hold's reason, or None."""
    return _HOLD


def invalidate_resolved_budget() -> None:
    """Drop the memoized absolute budget so the next gate check re-resolves
    a fractional ``HEAT_TPU_MEMORY_BUDGET`` against the LIVE backend: an
    elastic mesh reform changes the device set the fraction denominates
    over, and a stale denominator would admit against dead devices'
    memory."""
    global _RESOLVED_BUDGET
    _RESOLVED_BUDGET = None


def set_budget(budget=None, policy: Optional[str] = None):
    """(Re)arm the admission gate in-process: ``budget`` as
    :func:`parse_budget` accepts (None disarms), ``policy`` one of
    ``warn``/``raise``/``drain``. Returns the previous ``(budget, policy)``
    pair. Re-arming clears the once-per-key warn ledger and the resolved
    fractional budget."""
    global _BUDGET_RAW, _POLICY, _RESOLVED_BUDGET
    prev = (_BUDGET_RAW, _POLICY)
    _BUDGET_RAW = parse_budget(budget)
    if policy is not None:
        if policy not in _POLICIES:
            raise ValueError(f"policy must be one of {_POLICIES}, got {policy!r}")
        _POLICY = policy
    _RESOLVED_BUDGET = None
    _WARNED_KEYS.clear()
    return prev


def _device_bytes_limit() -> Optional[int]:
    """Per-host accountable device memory: the min bytes_limit over local
    devices x their count, where the backend exposes memory_stats (TPU
    does; forced-host CPU does not)."""
    try:
        import jax

        limits = []
        for d in jax.local_devices():
            stats = d.memory_stats()
            if stats and stats.get("bytes_limit"):
                limits.append(int(stats["bytes_limit"]))
        if limits:
            return min(limits) * len(limits)
    except Exception:  # noqa: BLE001 - backend-dependent probe only
        pass
    return None


def _host_bytes_total() -> Optional[int]:
    try:
        return int(os.sysconf("SC_PAGE_SIZE")) * int(os.sysconf("SC_PHYS_PAGES"))
    except (ValueError, OSError, AttributeError):  # pragma: no cover - non-POSIX
        return None


def _resolve_budget() -> Optional[int]:
    """The absolute byte budget: fractions resolve against device memory
    where the backend reports a limit, host physical memory otherwise
    (forced-host CPU meshes — the dev config); memoized."""
    global _RESOLVED_BUDGET
    if _BUDGET_RAW is None:
        return None
    if isinstance(_BUDGET_RAW, int):
        return _BUDGET_RAW
    if _RESOLVED_BUDGET is None:
        base = _device_bytes_limit() or _host_bytes_total()
        if base is None:
            return None  # nothing to take a fraction of: gate stays open
        _RESOLVED_BUDGET = int(_BUDGET_RAW * base)
    return _RESOLVED_BUDGET


def budget_info(resolve: bool = False) -> Dict[str, Any]:
    """The gate's configuration + counters: the raw knob, the resolved byte
    budget (None = disarmed/unresolved), the policy, and
    :func:`gate_stats`. A fractional budget is only resolved on demand
    (``resolve=True``) or once a gate check already resolved it — resolving
    probes the backend's device memory, and this function is called from
    ``telemetry.report()``, which must never initialize the backend."""
    if _BUDGET_RAW is None:
        budget_bytes = None
    elif isinstance(_BUDGET_RAW, int):
        budget_bytes = _BUDGET_RAW
    elif resolve or _RESOLVED_BUDGET is not None:
        budget_bytes = _resolve_budget()
    else:
        budget_bytes = None  # fraction, not yet resolved: stay backend-free
    return {
        "budget": _BUDGET_RAW,
        "budget_bytes": budget_bytes,
        "policy": _POLICY,
        **gate_stats(),
    }


def gate_stats() -> Dict[str, int]:
    """Admission-gate counters: ``checks``/``allowed``/``exceeded`` plus the
    per-policy outcomes (``warned``/``raised``/``drains``/``drained_roots``)
    — the assertable surface the budget-policy tests pin."""
    return dict(_GATE_STATS)


def admit(program: str, family: str, static_peak: int, source: str, drain_fn=None) -> None:
    """The headroom check at the fused-program dispatch seam: projected
    bytes = live ledger total + ``static_peak`` (the program's memoized XLA
    ``memory_analysis`` peak when available — ``source="static"`` — else the
    operand+result estimate). Within budget: returns. Over budget: applies
    the armed policy (see module docstring). Reentrant drains are admitted
    unconditionally — they free memory, they don't claim it. An active
    :func:`admission_hold` refuses every new admission regardless of budget
    state — the elastic supervisor's stop-the-world window."""
    global _IN_GATE
    if _IN_GATE:
        return
    if _HOLD is not None:
        _GATE_STATS["held"] += 1
        raise MemoryBudgetExceeded(
            f"dispatch admission held ({_HOLD}) for program {program} "
            f"({family}) — the chain is left pending and dispatches once the "
            "hold lifts (elastic drain/reform in progress)"
        )
    if _BUDGET_RAW is None:
        return
    budget = _resolve_budget()
    if budget is None:
        return
    _GATE_STATS["checks"] += 1
    # fast path: one deduped total, no attribution, no sort — the per-
    # dispatch cost of an armed gate. The full attributed sample runs only
    # when this total sets a new watermark (banking the owner split at the
    # peak) or on the over-budget path (the warning names owners).
    live = _scan_total()
    if live > _WATERMARK["bytes"]:
        sample("gate", force=True)
    projected = live + int(static_peak)
    if projected <= budget:
        _GATE_STATS["allowed"] += 1
        return
    _GATE_STATS["exceeded"] += 1
    policy = _POLICY
    drained = None
    if policy == "drain" and drain_fn is not None:
        _GATE_STATS["drains"] += 1
        _IN_GATE = True
        try:
            drained = int(drain_fn() or 0)
        finally:
            _IN_GATE = False
        _GATE_STATS["drained_roots"] += drained
        live = _scan_total()
        projected = live + int(static_peak)
    if telemetry._MODE >= 2:
        telemetry.record_event(
            "memory_gate",
            program=program, policy=policy, projected=projected,
            live=live, static_peak=int(static_peak), budget=budget,
            drained=drained, over=projected > budget,
        )
    if projected <= budget:
        _GATE_STATS["allowed"] += 1
        return
    if policy != "raise" and program in _WARNED_KEYS:
        # steady over-budget state, already warned for this key: nothing
        # will be emitted, so skip the attributed scan entirely
        return
    # the owners ranking is only paid when a warning/raise actually fires
    snap = _scan()
    owners = ", ".join(
        f"{o} {_fmt_bytes(b)}"
        for o, b in sorted(snap["by_owner"].items(), key=lambda kv: -kv[1])[:4]
    )
    msg = (
        f"memory budget {_fmt_bytes(budget)} exceeded: projected "
        f"{_fmt_bytes(projected)} (live {_fmt_bytes(live)} + static peak "
        f"{_fmt_bytes(static_peak)} [{source}]) for program {program} "
        f"({family}); top live owners: {owners or 'none'}"
    )
    if policy == "raise":
        _GATE_STATS["raised"] += 1
        raise MemoryBudgetExceeded(
            msg + " — the chain is left pending; lift the budget "
            "(memledger.set_budget) or free buffers, then force again"
        )
    if program not in _WARNED_KEYS:
        _WARNED_KEYS.add(program)
        _GATE_STATS["warned"] += 1
        suffix = (
            f" — drained {drained} outstanding root(s), still over budget"
            if policy == "drain"
            else ""
        )
        warnings.warn(MemoryBudgetWarning(msg + suffix), stacklevel=5)


# ----------------------------------------------------------------------
# OOM forensics
# ----------------------------------------------------------------------
_OOM_MARKERS = ("resource_exhausted", "resource exhausted", "out of memory", "memory.exhausted")

_LAST_OOM: Optional[Dict[str, Any]] = None


def is_oom(exc: BaseException) -> bool:
    """Whether ``exc`` is device memory exhaustion: ``MemoryError``, an
    ``XlaRuntimeError``/``RESOURCE_EXHAUSTED``-shaped backend error, or an
    injected ``memory.exhausted`` fault (its message carries the site)."""
    if isinstance(exc, MemoryError):
        return True
    text = (type(exc).__name__ + ": " + str(exc)).lower()
    return any(marker in text for marker in _OOM_MARKERS)


def record_oom(
    exc: BaseException,
    program: Optional[str] = None,
    family: Optional[str] = None,
    static_peak: Optional[int] = None,
    top: int = 5,
) -> Dict[str, Any]:
    """Build, store and warn the ranked OOM diagnostic for a dispatch that
    died of memory exhaustion: the failing program's key/family/static peak,
    the owner-attributed ledger with the top live buffers, the last-N
    ``dispatch`` events from the trace timeline (verbose mode), and the
    gate configuration. Called by ``fusion.force`` *before* the guarded
    degrade path so the evidence survives the recovery; returns the report
    (also via :func:`last_oom`)."""
    global _LAST_OOM
    led = ledger(top=top)
    recent = [
        {"program": ev.get("program"), "roots": ev.get("roots"), "ts": ev.get("ts")}
        for ev in telemetry.events()
        if ev.get("kind") == "dispatch"
    ][-5:]
    report = {
        "error": repr(exc),
        "program": program,
        "family": family,
        "static_peak_bytes": None if static_peak is None else int(static_peak),
        "live_total_bytes": led["total_bytes"],
        "by_owner": dict(led["by_owner"]),
        "top_buffers": list(led["top"]),
        "recent_dispatches": recent,
        "watermark_bytes": _WATERMARK["bytes"],
        "budget": budget_info(),
    }
    _LAST_OOM = report
    if telemetry._MODE:
        telemetry.record_event("memory_oom", program=program, family=family,
                               error=repr(exc), live=led["total_bytes"])
    owners = ", ".join(
        f"{o} {_fmt_bytes(b)}"
        for o, b in sorted(led["by_owner"].items(), key=lambda kv: -kv[1])[:4]
    )
    tops = "; ".join(
        f"{_fmt_bytes(b['nbytes'])} {b['owner']} {b['dtype']}{b['shape']}"
        for b in led["top"][:3]
    )
    peak = "unknown" if static_peak is None else _fmt_bytes(static_peak)
    warnings.warn(
        MemoryExhaustedWarning(
            f"device memory exhausted dispatching program {program or '<eager>'} "
            f"({family or '?'}; static peak {peak}): {exc!r}. Live buffers "
            f"{_fmt_bytes(led['total_bytes'])} by owner: {owners or 'none'}. "
            f"Largest: {tops or 'none'}. Full diagnostic via "
            "memledger.last_oom(); the chain degrades to per-op eager replay"
        ),
        stacklevel=5,
    )
    try:
        # black-box the OOM: the flight ring holds the dispatches that led
        # here, and the bundle embeds this report (lazy import — this module
        # must stay importable without the health layer)
        from . import health_runtime

        health_runtime.auto_dump("oom")
    except Exception as dump_exc:  # pragma: no cover - import-order safety
        warnings.warn(f"flight auto-dump after OOM failed: {dump_exc!r}", stacklevel=5)
    return report


def last_oom() -> Optional[Dict[str, Any]]:
    """The most recent OOM forensic report (None = no OOM seen)."""
    return _LAST_OOM


def _fmt_bytes(n) -> str:
    try:
        n = float(n)
    except (TypeError, ValueError):
        return str(n)
    for unit in ("B", "KiB", "MiB", "GiB", "TiB"):
        if abs(n) < 1024.0 or unit == "TiB":
            return f"{int(n)} B" if unit == "B" else f"{n:.1f} {unit}"
        n /= 1024.0
    return f"{n:.1f} TiB"  # pragma: no cover - loop always returns


def reset() -> None:
    """Zero the watermark, gate counters, warn ledger and the stored OOM
    report (the attribution registry stays — it tracks live arrays, not
    session state)."""
    global _LAST_OOM
    reset_watermark()
    for k in _GATE_STATS:
        _GATE_STATS[k] = 0
    _WARNED_KEYS.clear()
    _LAST_OOM = None


# register the sampling hook with telemetry (set-attribute, not import:
# telemetry must stay importable before this module)
telemetry._MEM_HOOK = note

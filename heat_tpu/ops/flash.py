"""Hand-tiled pallas flash-attention kernel for TPU.

The scan-based :func:`heat_tpu.nn.attention.flash_attention` leaves the tile
schedule to XLA; this kernel owns it: the FULL (batch·head, q_block, k_block)
tiling lives on the pallas grid — K/V tiles are streamed HBM→VMEM one
(block_k, D) block per grid step by BlockSpecs (so pallas double-buffers the
fetch against the previous tile's compute, and sequence length is NOT capped
by VMEM), the two matmuls per tile hit the MXU, and the online-softmax state
(m, l, acc) lives in VMEM scratch that carries across the k-axis grid steps.
With ``causal=True`` tiles strictly above the diagonal skip their compute
via ``pl.when`` AND their K/V copies via a clamped (repeating) block index —
half the FLOPs and half the K/V traffic at a uniform grid.

The reference framework has no attention; this kernel is the long-context
hot-op analog of its densest compute path (the cdist tile kernel,
reference spatial/distance.py:16-134 → heat_tpu/ops/pairwise.py).

Layout: heads fold into the grid's leading axis ([B, H] → programs), head_dim
is the lane axis padded to 128, sequence is the sublane axis in (block, D)
tiles. VMEM holds one Q tile, one K/V tile pair (double-buffered), the
(block_q, D) f32 accumulator and two (block_q, 128) state columns — a few
hundred KB regardless of S.
"""

from __future__ import annotations

import functools
import math
from typing import Optional

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

__all__ = ["flash_attention_tpu", "pallas_attention_supported"]

_LANE = 128
_NEG_INF = -1e30  # large-negative instead of -inf: exp() underflows to 0 identically


def pallas_attention_supported(seq_len: int, head_dim: int) -> bool:
    """TPU backend present and the head fits the lane tile. K/V stream per
    block since the r05 grid rewrite, so sequence length no longer caps the
    kernel (the old whole-K/V-resident design topped out near 8k)."""
    try:
        on_tpu = jax.default_backend() in ("tpu", "axon")
    except Exception:  # pragma: no cover
        return False
    return on_tpu and head_dim <= 4 * _LANE


def _attn_kernel(
    q_ref, k_ref, v_ref, o_ref, m_ref, l_ref, acc_ref,
    *, scale, causal, block_q, block_k, sk, nk,
):
    """One (batch·head, q-block, k-block) grid step: fold one K/V tile into
    the online-softmax scratch state; finalize into ``o_ref`` on the last
    k-step. The k axis is the FASTEST grid dimension, so the scratch
    (m, l, acc) carries one q-block's state across its k sweep.

    bfloat16 inputs stay bfloat16 on both MXU contractions (scores and
    values, ``preferred_element_type=f32``) — casting to f32 would halve the
    MXU rate; the state is always f32. The scale is folded into the q tile
    once per k-step (cheap: (block_q, D) vs the (block_q, block_k) score).

    The state is kept 2-D with a 128-lane minor axis ((block_q, LANE), not
    (block_q,)): Mosaic lays 1-D vectors out with a replicated sublane, and
    chaining max / exp / where through that layout costs a relayout per
    k-tile — the same layout class that broke the Lloyd kernel outright
    (ops/lloyd.py). keepdims everywhere keeps the loop relayout-free."""
    iq = pl.program_id(1)
    jk = pl.program_id(2)
    q_idx0 = iq * block_q
    k0 = jk * block_k

    @pl.when(jk == 0)
    def _init():
        m_ref[:, :] = jnp.full_like(m_ref, _NEG_INF)
        l_ref[:, :] = jnp.zeros_like(l_ref)
        acc_ref[:, :] = jnp.zeros_like(acc_ref)

    # causal: tiles strictly above the diagonal contribute nothing — skip
    # their compute (the fetch is pipelined regardless; FLOPs halve)
    live = True
    if causal:
        live = k0 <= q_idx0 + (block_q - 1)

    @pl.when(live)
    def _tile():
        mm_dtype = q_ref.dtype if q_ref.dtype == jnp.bfloat16 else jnp.float32
        q = (q_ref[0].astype(jnp.float32) * scale).astype(mm_dtype)  # (block_q, D)
        kb = k_ref[0].astype(mm_dtype)  # (block_k, D)
        vb = v_ref[0].astype(mm_dtype)
        m = m_ref[:, :1]  # (block_q, 1) view of the state column
        l = l_ref[:, :1]
        s = jax.lax.dot_general(
            q, kb, (((1,), (1,)), ((), ())), preferred_element_type=jnp.float32
        )  # (block_q, block_k); scale pre-folded into q
        k_ids = k0 + jax.lax.broadcasted_iota(jnp.int32, (block_q, block_k), 1)
        keep = k_ids < sk  # mask sequence padding
        if causal:
            q_ids = q_idx0 + jax.lax.broadcasted_iota(jnp.int32, (block_q, block_k), 0)
            keep = keep & (q_ids >= k_ids)
        s = jnp.where(keep, s, _NEG_INF)
        m_new = jnp.maximum(m, jnp.max(s, axis=1, keepdims=True))
        p = jnp.exp(s - m_new)
        # rows with m_new == _NEG_INF are all-masked; zero their probabilities
        p = jnp.where(m_new > _NEG_INF / 2, p, 0.0)
        alpha = jnp.exp(m - m_new)  # (block_q, 1)
        l_new = alpha * l + jnp.sum(p, axis=1, keepdims=True)
        acc_ref[:, :] = alpha * acc_ref[:, :] + jax.lax.dot_general(
            p.astype(mm_dtype), vb, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
        )
        m_ref[:, :] = jnp.broadcast_to(m_new, m_ref.shape)
        l_ref[:, :] = jnp.broadcast_to(l_new, l_ref.shape)

    @pl.when(jk == nk - 1)
    def _finalize():
        l = l_ref[:, :1]
        denom = jnp.where(l > 0, l, 1.0)
        o_ref[0] = (acc_ref[:, :] / denom).astype(o_ref.dtype)


@functools.partial(
    jax.jit, static_argnames=("causal", "scale", "block_q", "block_k", "interpret")
)
def flash_attention_tpu(
    q: jax.Array,
    k: jax.Array,
    v: jax.Array,
    *,
    causal: bool = False,
    scale: Optional[float] = None,
    block_q: int = 256,
    block_k: int = 512,
    interpret: bool = False,
) -> jax.Array:
    """Pallas flash attention on [B, S, H, D] inputs (same contract as
    :func:`heat_tpu.nn.attention.flash_attention`).

    Default tiles are (256, 512): the r04 capture measured the kernel at its
    then-default (128, 128) tiles losing 0.65x to dense at 4k causal —
    128-wide MXU contractions are too small to amortize the per-tile
    softmax state updates; larger tiles raise arithmetic intensity per
    k-axis grid step (benchmarks/tpu_window.py stage_attention_sweep
    searches the schedule and records the winner)."""
    B, S, H, D = q.shape
    sk = k.shape[1]
    if scale is None:
        scale = 1.0 / math.sqrt(D)

    d_pad = max(_LANE, -(-D // _LANE) * _LANE)
    sq_pad = -(-S // block_q) * block_q
    sk_pad = -(-sk // block_k) * block_k

    def to_bhsd(x, s_pad):
        x = jnp.transpose(x, (0, 2, 1, 3)).reshape(B * H, x.shape[1], D)
        return jnp.pad(x, ((0, 0), (0, s_pad - x.shape[1]), (0, d_pad - D)))

    qf, kf, vf = to_bhsd(q, sq_pad), to_bhsd(k, sk_pad), to_bhsd(v, sk_pad)
    nq, nk = sq_pad // block_q, sk_pad // block_k

    if causal:
        # above-diagonal k-steps are compute-skipped by pl.when; clamping
        # their block index to the q-block's LAST live tile makes the index
        # repeat, and pallas skips the copy for a repeated index — so dead
        # steps move no HBM bytes either (the old fori_loop design's
        # never-read-above-diagonal guarantee, kept on the uniform grid)
        def kv_index(bh, iq, jk):
            last_live = (iq * block_q + (block_q - 1)) // block_k
            return (bh, jnp.minimum(jk, last_live), 0)

    else:
        def kv_index(bh, iq, jk):
            return (bh, jk, 0)

    out = pl.pallas_call(
        functools.partial(
            _attn_kernel,
            scale=scale,
            causal=causal,
            block_q=block_q,
            block_k=block_k,
            sk=sk,
            nk=nk,
        ),
        # k is the FASTEST axis: each q-block's online-softmax state carries
        # across its k sweep in VMEM scratch; pallas streams one K/V tile
        # per step (double-buffered against the previous tile's matmuls)
        grid=(B * H, nq, nk),
        in_specs=[
            pl.BlockSpec((1, block_q, d_pad), lambda bh, iq, jk: (bh, iq, 0)),
            pl.BlockSpec((1, block_k, d_pad), kv_index),
            pl.BlockSpec((1, block_k, d_pad), kv_index),
        ],
        out_specs=pl.BlockSpec((1, block_q, d_pad), lambda bh, iq, jk: (bh, iq, 0)),
        out_shape=jax.ShapeDtypeStruct((B * H, sq_pad, d_pad), q.dtype),
        scratch_shapes=[
            pltpu.VMEM((block_q, _LANE), jnp.float32),  # m (col 0 live)
            pltpu.VMEM((block_q, _LANE), jnp.float32),  # l
            pltpu.VMEM((block_q, d_pad), jnp.float32),  # acc
        ],
        interpret=interpret,
    )(qf, kf, vf)

    out = out[:, :S, :D].reshape(B, H, S, D)
    return jnp.transpose(out, (0, 2, 1, 3))

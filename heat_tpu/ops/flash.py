"""Hand-tiled pallas flash-attention kernel for TPU.

The scan-based :func:`heat_tpu.nn.attention.flash_attention` leaves the tile
schedule to XLA; this kernel owns it: the (q_block, k_block) tiling lives on
the pallas grid, Q/K/V tiles are staged HBM→VMEM by BlockSpecs, the two
matmuls per tile hit the MXU, and the online-softmax state (m, l, acc) stays
in registers/VMEM across the k-loop. With ``causal=True`` the k-loop bound is
computed from the query block's global offset, so tiles strictly above the
diagonal are never read — a ~2x FLOP/traffic saving XLA's scan cannot express
(its loop trip count is uniform).

The reference framework has no attention; this kernel is the long-context
hot-op analog of its densest compute path (the cdist tile kernel,
reference spatial/distance.py:16-134 → heat_tpu/ops/pairwise.py).

Layout: heads fold into the grid's leading axis ([B, H] → programs), head_dim
is the lane axis padded to 128, sequence is the sublane axis in (128, D)
tiles. K/V are presented per-program as the full (padded) sequence; VMEM
holds S·D·4·2 bytes of K+V per program, and the ``pallas_attention_supported``
gate caps that at 8 MB (S ≈ 8k at D=128) to leave headroom in the ~16 MB
VMEM for Q/O tiles and double buffering.
"""

from __future__ import annotations

import functools
import math
from typing import Optional

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl

__all__ = ["flash_attention_tpu", "pallas_attention_supported"]

_LANE = 128
_NEG_INF = -1e30  # large-negative instead of -inf: exp() underflows to 0 identically


def pallas_attention_supported(seq_len: int, head_dim: int) -> bool:
    """TPU backend present and K+V for one (batch, head) fit VMEM comfortably."""
    try:
        on_tpu = jax.default_backend() in ("tpu", "axon")
    except Exception:  # pragma: no cover
        return False
    d_pad = max(_LANE, -(-head_dim // _LANE) * _LANE)
    kv_bytes = 2 * seq_len * d_pad * 4
    return on_tpu and kv_bytes <= 8 * 1024 * 1024


def _attn_kernel(q_ref, k_ref, v_ref, o_ref, *, scale, causal, block_q, block_k, sk, nk):
    """One (batch·head, q-block) program: stream k/v tiles, fold online softmax.

    bfloat16 inputs stay bfloat16 on both MXU contractions (scores and
    values, ``preferred_element_type=f32``) — casting to f32 would halve the
    MXU rate and double VMEM pressure; the online-softmax state (m, l, acc)
    is always f32. The scale is folded into the q tile once, instead of
    multiplying every (block_q, block_k) score tile."""
    iq = pl.program_id(1)
    mm_dtype = q_ref.dtype if q_ref.dtype == jnp.bfloat16 else jnp.float32
    q = (q_ref[0].astype(jnp.float32) * scale).astype(mm_dtype)  # (block_q, D)
    q_idx0 = iq * block_q

    if causal:
        # highest key index any row of this q-block may see is q_idx0+block_q-1
        # (all-int32 arithmetic: x64 mode would otherwise promote and trip lax.div)
        one = jnp.int32(1)
        nk_eff = jnp.minimum(
            jnp.int32(nk),
            (q_idx0 + jnp.int32(block_q) + jnp.int32(block_k) - one) // jnp.int32(block_k),
        )
    else:
        nk_eff = jnp.int32(nk)

    # online-softmax state is kept 2-D ((block_q, 1), not (block_q,)):
    # Mosaic lays 1-D vectors out with a replicated sublane, and chaining
    # max / exp / where through that layout costs a relayout per k-tile —
    # the same layout class that broke the Lloyd kernel outright
    # (ops/lloyd.py). keepdims everywhere keeps the loop relayout-free.
    def body(jk, carry):
        m, l, acc = carry  # m, l: (block_q, 1)
        k0 = jk * block_k
        kb = k_ref[0, pl.ds(k0, block_k), :].astype(mm_dtype)  # (block_k, D)
        vb = v_ref[0, pl.ds(k0, block_k), :].astype(mm_dtype)
        s = jax.lax.dot_general(
            q, kb, (((1,), (1,)), ((), ())), preferred_element_type=jnp.float32
        )  # (block_q, block_k); scale pre-folded into q
        k_ids = k0 + jax.lax.broadcasted_iota(jnp.int32, (block_q, block_k), 1)
        keep = k_ids < sk  # mask sequence padding
        if causal:
            q_ids = q_idx0 + jax.lax.broadcasted_iota(jnp.int32, (block_q, block_k), 0)
            keep = keep & (q_ids >= k_ids)
        s = jnp.where(keep, s, _NEG_INF)
        m_new = jnp.maximum(m, jnp.max(s, axis=1, keepdims=True))
        p = jnp.exp(s - m_new)
        # rows with m_new == _NEG_INF are all-masked; zero their probabilities
        p = jnp.where(m_new > _NEG_INF / 2, p, 0.0)
        alpha = jnp.exp(m - m_new)  # (block_q, 1)
        l = alpha * l + jnp.sum(p, axis=1, keepdims=True)
        acc = alpha * acc + jax.lax.dot_general(
            p.astype(mm_dtype), vb, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
        )
        return m_new, l, acc

    m0 = jnp.full((block_q, 1), _NEG_INF, jnp.float32)
    l0 = jnp.zeros((block_q, 1), jnp.float32)
    a0 = jnp.zeros((block_q, q.shape[1]), jnp.float32)
    m, l, acc = jax.lax.fori_loop(0, nk_eff, body, (m0, l0, a0))
    denom = jnp.where(l > 0, l, 1.0)
    o_ref[0] = (acc / denom).astype(o_ref.dtype)


@functools.partial(
    jax.jit, static_argnames=("causal", "scale", "block_q", "block_k", "interpret")
)
def flash_attention_tpu(
    q: jax.Array,
    k: jax.Array,
    v: jax.Array,
    *,
    causal: bool = False,
    scale: Optional[float] = None,
    block_q: int = 256,
    block_k: int = 512,
    interpret: bool = False,
) -> jax.Array:
    """Pallas flash attention on [B, S, H, D] inputs (same contract as
    :func:`heat_tpu.nn.attention.flash_attention`).

    Default tiles are (256, 512): the r04 capture measured the kernel at its
    then-default (128, 128) tiles losing 0.65x to dense at 4k causal —
    128-wide MXU contractions are too small to amortize the per-tile
    softmax state updates; larger tiles raise arithmetic intensity per
    fori_loop step (benchmarks/tpu_window.py stage_attention_sweep searches
    the schedule and records the winner)."""
    B, S, H, D = q.shape
    sk = k.shape[1]
    if scale is None:
        scale = 1.0 / math.sqrt(D)

    d_pad = max(_LANE, -(-D // _LANE) * _LANE)
    sq_pad = -(-S // block_q) * block_q
    sk_pad = -(-sk // block_k) * block_k

    def to_bhsd(x, s_pad):
        x = jnp.transpose(x, (0, 2, 1, 3)).reshape(B * H, x.shape[1], D)
        return jnp.pad(x, ((0, 0), (0, s_pad - x.shape[1]), (0, d_pad - D)))

    qf, kf, vf = to_bhsd(q, sq_pad), to_bhsd(k, sk_pad), to_bhsd(v, sk_pad)
    nq, nk = sq_pad // block_q, sk_pad // block_k

    out = pl.pallas_call(
        functools.partial(
            _attn_kernel,
            scale=scale,
            causal=causal,
            block_q=block_q,
            block_k=block_k,
            sk=sk,
            nk=nk,
        ),
        grid=(B * H, nq),
        in_specs=[
            pl.BlockSpec((1, block_q, d_pad), lambda bh, iq: (bh, iq, 0)),
            pl.BlockSpec((1, sk_pad, d_pad), lambda bh, iq: (bh, 0, 0)),
            pl.BlockSpec((1, sk_pad, d_pad), lambda bh, iq: (bh, 0, 0)),
        ],
        out_specs=pl.BlockSpec((1, block_q, d_pad), lambda bh, iq: (bh, iq, 0)),
        out_shape=jax.ShapeDtypeStruct((B * H, sq_pad, d_pad), q.dtype),
        interpret=interpret,
    )(qf, kf, vf)

    out = out[:, :S, :D].reshape(B, H, S, D)
    return jnp.transpose(out, (0, 2, 1, 3))

"""TPU pallas kernels for the hot ops.

The reference framework's compute kernels live in libtorch (reference
SURVEY.md vital stats: no native code in-repo, all kernels delegated). The
TPU-native analog is XLA for everything fusion can handle, plus hand-written
pallas kernels where the schedule matters. Current contents: the fused
pairwise-distance tile kernel (:mod:`heat_tpu.ops.pairwise`) — an
exact-numerics tiled alternative to the broadcast expression with a
guaranteed O(n·m + (n+m)·f) HBM footprint (see its module docstring for the
measured comparison against XLA's autofusion, which the default
``spatial.cdist`` path uses).
"""

from . import flash, pairwise
from .flash import flash_attention_tpu
from .pairwise import pairwise_distance

__all__ = ["flash", "pairwise", "pairwise_distance", "flash_attention_tpu"]

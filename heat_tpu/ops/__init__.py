"""TPU pallas kernels for the hot ops.

The reference framework's compute kernels live in libtorch (reference
SURVEY.md vital stats: no native code in-repo, all kernels delegated). The
TPU-native analog is XLA for everything fusion can handle, plus hand-written
pallas kernels where the schedule matters. Current contents:

- :mod:`~heat_tpu.ops.flash` — flash attention with causal tile skipping
  (consumed by ``nn.attention`` on TPU).
- :mod:`~heat_tpu.ops.pairwise` — fused pairwise-distance tiles, an
  exact-numerics alternative to the broadcast expression with a guaranteed
  O(n·m + (n+m)·f) HBM footprint (see its docstring for the measured
  comparison against XLA's autofusion, which the default ``spatial.cdist``
  path uses).
- :mod:`~heat_tpu.ops.lloyd` — single-pass fused Lloyd iteration for
  k-means (single-device and shard_map forms; measured beside the jnp path
  in ``bench.py``).
"""

from . import flash, lloyd, pairwise
from .flash import flash_attention_tpu
from .lloyd import fused_lloyd_iter, fused_lloyd_iter_sharded, fused_lloyd_run
from .pairwise import pairwise_distance

__all__ = [
    "flash",
    "lloyd",
    "pairwise",
    "pairwise_distance",
    "flash_attention_tpu",
    "fused_lloyd_iter",
    "fused_lloyd_iter_sharded",
    "fused_lloyd_run",
]

"""Fused single-pass Lloyd iteration (pallas).

The jnp Lloyd step (`cluster/kmeans.py:_lloyd_iter`) necessarily reads the
(n, f) data from HBM twice per iteration — once for the assignment matmul
``x @ cᵀ`` and once for the update matmul ``onehotᵀ @ x`` — and materializes
the (n, k) one-hot operand for the MXU. At the benchmark shape (10M x 16
f32) the iteration is pure HBM bandwidth, so the floor is set by bytes
moved, not FLOPs.

This kernel streams each row block into VMEM ONCE and produces everything
the iteration needs in that single pass:

    score   = |c|² − 2·xb @ cᵀ          (block, k)   MXU
    labels  = argmin(score)              (block,)
    inertia += Σ min(score)              scalar accumulator
    onehot  = (labels == arange(k))      (block, k)  VMEM-only
    sums   += onehotᵀ @ xb               (k, f)      MXU accumulator
    counts += Σ onehot                   (k,)        accumulator

HBM traffic per iteration: n·f reads, and NOTHING per-row written — the
kernel emits only the (k, f)/(1, k)/(1, 1) accumulators. Labels are not an
iteration output at all: a ``(block, 1)`` label block lane-pads 1 → 128 in
VMEM (it cost 8 MB of the 16 MB scoped budget — the r04 OOM) and a
``(n, 1)`` array tiles to ~128x its size in HBM, so per-iteration label
writes are exactly the waste a TPU-first design must avoid. The final
assignment is a separate fused jnp epilogue (`_assign_labels`) executed
once per program against the centers of the last iteration — the same
labels the jnp oracle reports, at the cost of one extra data read per
*program* (≤8 iterations), not per iteration. This is ~2x less traffic
than the fused-by-XLA jnp path (which cannot merge two contractions over
the same operand into one read). The centroid update (k x f, tiny) runs
outside.

The feature axis is NOT padded to the 128-lane width in HBM — blocks are
DMA'd as (block, f) and padded only in VMEM — so the bandwidth advantage
survives small f (f=16 padded in HBM would octuple the bytes).

This kernel IS the product path: ``cluster.KMeans.fit`` dispatches here on
TPU (``fused_supported`` / ``fused_sharded_supported``), keeping the jnp
path as the fallback and numerical oracle; bench.py's primary kmeans metric
measures whichever path the product dispatches (``lloyd_path`` in the
record), with the other path alongside (``lloyd_jnp_iters_per_sec`` /
``lloyd_fused_vs_jnp``). :func:`fused_lloyd_iter` is
single-device (its pallas_call has no partitioning spec);
:func:`fused_lloyd_iter_sharded` / :func:`fused_lloyd_run_sharded` are the
multi-chip forms: a shard_map wrapper running the kernel per device and
merging the (k, f) accumulators with one psum — the exact collective budget
of the jnp path.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

__all__ = [
    "fused_lloyd_iter",
    "fused_lloyd_iter_sharded",
    "fused_lloyd_run",
    "fused_lloyd_run_sharded",
    "fused_sharded_supported",
    "fused_supported",
]

def _block_rows(f: int) -> int:
    """Rows per grid step, sized against the REAL scoped-VMEM footprint on a
    v5e (16 MB limit). Everything row-shaped is lane-padded to a multiple of
    128: the double-buffered (block, f) input AND the kernel's live vector
    intermediates — xb, score, onehot and the masked-min chain all occupy
    block x 128 lanes of stack regardless of f or k. Budget ≈ 4 · block ·
    (2 · lane_pad(f) + 4 · 128) bytes ≤ 12 MB (headroom for the (k, f)
    accumulators and csq/cT). Measured: block=8192 at f=16 hit the 16 MB
    scoped limit to within 1.5 KB even with NO per-row output."""
    lanes = 128 * ((f + 127) // 128)
    blk = (12 << 20) // (4 * (2 * lanes + 4 * 128))
    return max(512, min(8192, blk // 8 * 8))


def fused_supported(n: int, f: int, k: int) -> bool:
    """TPU backend, single device (the kernel has no partitioning spec —
    a sharded operand would be gathered), and lane-safe k."""
    try:
        backend_ok = jax.default_backend() in ("tpu", "axon")
        single = len(jax.devices()) == 1
    except Exception:  # pragma: no cover
        return False
    return backend_ok and single and f <= 512 and k <= 128


def fused_sharded_supported(f: int, k: int) -> bool:
    """TPU backend and lane-safe shapes; device count is irrelevant (the
    shard_map wrapper runs the kernel per device)."""
    try:
        backend_ok = jax.default_backend() in ("tpu", "axon")
    except Exception:  # pragma: no cover
        return False
    return backend_ok and f <= 512 and k <= 128


def _lloyd_kernel(
    x_ref,
    csq_ref,
    cT_ref,
    nvalid_ref,
    sums_ref,
    counts_ref,
    inertia_ref,
    *,
    k: int,
    block: int,
):
    """One (block, f) row block; accumulators live across the whole grid.
    Rows at index >= nvalid (tail padding: ragged sizes, or a device's share
    of the global padding under the sharded wrapper) are masked out of every
    accumulator. n_valid is a runtime (1,1) scalar operand so each device
    can carry its own count."""
    i = pl.program_id(0)

    # EVERY intermediate stays 2-D. Mosaic lays a 1-D (block,) value out as
    # vector<1xblockxf32> with a replicated sublane, and chaining argmin /
    # where / reduce through that layout hits "Invalid relayout: Non-singleton
    # logical dimension is replicated in destination but not in source"
    # (observed on a real v5e at block=8192; benchmarks/TPU_WINDOW_r04.json
    # mosaic_variants passes each construct alone — only the 1-D chain fails).
    # keepdims=True everywhere sidesteps the layout class entirely.
    klane = jax.lax.broadcasted_iota(jnp.int32, (1, k), 1)
    rows = i * block + jax.lax.broadcasted_iota(jnp.int32, (block, 1), 0)
    valid_b = rows < nvalid_ref[0, 0]  # (BLOCK, 1) bool

    # Pad-region content is UNSPECIFIED (dndarray.parray contract) — inf/NaN
    # there would poison the accumulators through 0·inf = NaN in the sums
    # matmul, so zero invalid rows rather than relying on multiplicative
    # masking downstream.
    xb = jnp.where(valid_b, x_ref[:, :], 0)  # (block, f)
    valid = valid_b.astype(xb.dtype)

    # (block, k) assignment scores; |x|² omitted (row-constant for argmin)
    score = csq_ref[:, :] - 2.0 * jnp.dot(
        xb, cT_ref[:, :], preferred_element_type=jnp.float32
    )
    labels2d = jnp.argmin(score, axis=1, keepdims=True).astype(jnp.int32)  # (block, 1)
    onehot = (labels2d == klane).astype(xb.dtype) * valid  # (BLOCK, k)

    @pl.when(i == 0)
    def _init():
        sums_ref[:, :] = jnp.zeros_like(sums_ref)
        counts_ref[:, :] = jnp.zeros_like(counts_ref)
        inertia_ref[:, :] = jnp.zeros_like(inertia_ref)

    sums_ref[:, :] += jnp.dot(onehot.T, xb, preferred_element_type=jnp.float32).astype(
        sums_ref.dtype
    )
    counts_ref[:, :] += jnp.sum(onehot, axis=0, keepdims=True).astype(counts_ref.dtype)
    # where, not multiply: even a finite-but-garbage pad score must not leak,
    # and NaN·0 = NaN would defeat a multiplicative mask
    min2d = jnp.min(score, axis=1, keepdims=True)  # (block, 1)
    masked_min = jnp.where(valid_b, min2d, 0.0)  # (block, 1)
    inertia_ref[:, :] += jnp.sum(masked_min, dtype=inertia_ref.dtype)[None, None]


def _kernel_call(data, centers, k: int, n_valid, interpret: bool):
    """Pad, tile, and invoke the kernel on one device's rows.

    ``n_valid`` is a traced int32 scalar: rows at local index >= n_valid are
    masked out of the accumulators (tail padding; under shard_map, each
    device's share of the global pad). Returns the raw (sums, counts,
    inertia) accumulators — labels are deliberately NOT a kernel output
    (see the module docstring on lane padding).
    """
    n, f = data.shape
    # downcast BEFORE deriving cT so the kernel never mixes f64 operands
    # (Mosaic cannot lower f64; interpret/CPU would silently promote)
    x = data.astype(jnp.float32) if data.dtype == jnp.float64 else data
    csq = jnp.sum(centers * centers, axis=1, dtype=jnp.float32)[None, :]  # (1, k)
    cT = centers.T.astype(x.dtype)  # (f, k)
    block = _block_rows(f)
    n_pad = -(-n // block) * block
    if n_pad != n:
        x = jnp.pad(x, ((0, n_pad - n), (0, 0)))
    nv = jnp.reshape(n_valid.astype(jnp.int32), (1, 1))

    return pl.pallas_call(
        functools.partial(_lloyd_kernel, k=k, block=block),
        out_shape=(
            jax.ShapeDtypeStruct((k, f), jnp.float32),
            jax.ShapeDtypeStruct((1, k), jnp.float32),
            jax.ShapeDtypeStruct((1, 1), jnp.float32),
        ),
        grid=(n_pad // block,),
        in_specs=[
            pl.BlockSpec((block, f), lambda i: (i, 0), memory_space=pltpu.VMEM),
            pl.BlockSpec((1, k), lambda i: (0, 0), memory_space=pltpu.VMEM),
            pl.BlockSpec((f, k), lambda i: (0, 0), memory_space=pltpu.VMEM),
            pl.BlockSpec((1, 1), lambda i: (0, 0), memory_space=pltpu.VMEM),
        ],
        out_specs=(
            pl.BlockSpec((k, f), lambda i: (0, 0), memory_space=pltpu.VMEM),
            pl.BlockSpec((1, k), lambda i: (0, 0), memory_space=pltpu.VMEM),
            pl.BlockSpec((1, 1), lambda i: (0, 0), memory_space=pltpu.VMEM),
        ),
        interpret=interpret,
    )(x, csq, cT, nv)


def _assign_labels(data: jax.Array, centers: jax.Array) -> jax.Array:
    """The assignment step alone, as one fused XLA pass: labels w.r.t.
    ``centers``. Runs ONCE per program as the label epilogue — per-row labels
    are not a kernel output (module docstring)."""
    x32 = data.astype(jnp.float32)
    c32 = centers.astype(jnp.float32)
    score = jnp.sum(c32 * c32, axis=1)[None, :] - 2.0 * (x32 @ c32.T)
    return jnp.argmin(score, axis=1).astype(jnp.int32)


@functools.partial(jax.jit, static_argnames=("k", "interpret"))
def fused_lloyd_iter(
    data: jax.Array, centers: jax.Array, k: int, xsq_sum=None, interpret: bool = False
):
    """One Lloyd iteration in a single accumulator pass (+ label epilogue).

    Returns ``(new_centers, labels, inertia, shift)`` with the same contract
    as ``cluster.kmeans._lloyd_iter`` (inertia includes the Σ|x|² term;
    labels are the assignment against the INPUT centers).
    ``xsq_sum`` is the loop-invariant Σ|x|²; pass it from outside an
    iteration loop, or it is computed here (costing the one extra data read
    the kernel exists to avoid).
    """
    n = data.shape[0]
    sums, counts, inertia = _kernel_call(
        data, centers, k, jnp.asarray(n, jnp.int32), interpret
    )
    if xsq_sum is None:
        x32 = data.astype(jnp.float32)
        xsq_sum = jnp.sum(x32 * x32)
    new_centers, inertia_full, shift = _finalize(sums, counts, inertia, centers, xsq_sum)
    return new_centers, _assign_labels(data, centers), inertia_full, shift


def _finalize(sums, counts, inertia, centers, xsq_sum):
    """Shared epilogue: centroid update (empty clusters keep their center),
    inertia restoration (+Σ|x|²), and the convergence shift. One body for
    the single-device and sharded paths so their numerics cannot drift."""
    counts = counts[0]
    new_centers = jnp.where(
        counts[:, None] > 0,
        sums / jnp.maximum(counts[:, None], 1.0),
        centers.astype(jnp.float32),
    ).astype(centers.dtype)
    inertia_full = jnp.maximum(inertia[0, 0] + xsq_sum, 0.0)
    shift = jnp.sum((new_centers - centers).astype(jnp.float32) ** 2)
    return new_centers, inertia_full, shift


@functools.partial(jax.jit, static_argnames=("k", "n_steps", "interpret"))
def fused_lloyd_run(
    data: jax.Array, centers: jax.Array, k: int, n_steps: int, interpret: bool = False
):
    """``n_steps`` fused iterations in one XLA program (the pallas analog of
    ``cluster.kmeans._lloyd_run``): Σ|x|² hoisted, one kernel pass per step,
    labels from ONE epilogue pass against the last iteration's input centers
    (the jnp oracle's exact label convention)."""
    x32 = data.astype(jnp.float32)
    xsq_sum = jnp.sum(x32 * x32)

    def body(i, carry):
        centers, _, _, _ = carry
        sums, counts, inertia = _kernel_call(
            data, centers, k, jnp.asarray(data.shape[0], jnp.int32), interpret
        )
        new_centers, inertia_full, shift = _finalize(
            sums, counts, inertia, centers, xsq_sum
        )
        return (new_centers, centers, inertia_full, shift)

    acc = jnp.zeros((), jnp.float32)
    centers, used, inertia, shift = jax.lax.fori_loop(
        0, n_steps, body, (centers, centers, acc, acc)
    )
    return centers, _assign_labels(data, used), inertia, shift


def fused_lloyd_iter_sharded(
    data: jax.Array,
    centers: jax.Array,
    k: int,
    comm,
    n_global: int,
    xsq_sum=None,
    interpret: bool = False,
):
    """One fused Lloyd iteration over a row-sharded operand.

    ``data`` is the PHYSICAL payload (``DNDarray.parray``): row count a
    multiple of the mesh size, suffix-padded when the logical ``n_global``
    is ragged. Each device runs the single-pass kernel on its own block —
    masking its share of the global padding — and the (k, f)/(k,)/scalar
    accumulators merge with one ``psum``. Labels come from the shared jnp
    epilogue on the row-sharded global view (no collectives: the matmul
    against replicated centers and the argmin are row-local), sliced to the
    logical length ``n_global``.

    Same return contract as :func:`fused_lloyd_iter`. The whole iteration
    (shard_map + epilogue) is jitted, cached per (mesh, k, shapes).
    """
    fn = _sharded_fn(comm.mesh, comm.axis_name, comm.size, k, int(n_global), bool(interpret))
    return fn(data, centers, xsq_sum)


def _sharded_iter_fn(mesh, axis, k, n_global, interpret):
    """Traced (data, centers, xsq_sum) -> iteration tuple over a row-sharded
    physical payload — the shared body of the per-iteration and fused-run
    sharded entry points."""
    from jax.sharding import PartitionSpec as P

    def device_step(xl, c):
        local_rows = xl.shape[0]
        idx = jax.lax.axis_index(axis)
        local_valid = jnp.clip(n_global - idx * local_rows, 0, local_rows)
        sums, counts, inertia = _kernel_call(xl, c, k, local_valid, interpret)
        sums = jax.lax.psum(sums, axis)
        counts = jax.lax.psum(counts, axis)
        inertia = jax.lax.psum(inertia, axis)
        return sums, counts, inertia

    def step(data, centers, xsq_sum):
        sums, counts, inertia = jax.shard_map(
            device_step,
            mesh=mesh,
            in_specs=(P(axis, None), P()),
            out_specs=(P(), P(), P()),
            check_vma=False,  # pallas_call outputs carry no vma annotation
        )(data, centers)
        return _finalize(sums, counts, inertia, centers, xsq_sum)

    return step


def _logical_xsq_sum(data, n_global):
    # Σ|x|² over the LOGICAL rows only: the physical pad region's content is
    # unspecified (dndarray.parray contract) — never fold it into the inertia
    x32 = data[:n_global].astype(jnp.float32)
    return jnp.sum(x32 * x32)


@functools.lru_cache(maxsize=None)
def _sharded_fn(mesh, axis, p, k, n_global, interpret):
    """Jitted sharded iteration, cached per static config (the
    attention.py:_ring_attention_fn closure-cache pattern — comm objects are
    unhashable, their mesh/axis are)."""
    step = _sharded_iter_fn(mesh, axis, k, n_global, interpret)

    @jax.jit
    def run(data, centers, xsq_sum):
        if xsq_sum is None:
            xsq_sum = _logical_xsq_sum(data, n_global)
        new_centers, inertia, shift = step(data, centers, xsq_sum)
        labels = _assign_labels(data, centers)[:n_global]
        return new_centers, labels, inertia, shift

    return run


def fused_lloyd_run_sharded(
    data: jax.Array,
    centers: jax.Array,
    k: int,
    comm,
    n_global: int,
    n_steps: int,
    interpret: bool = False,
):
    """``n_steps`` fused sharded iterations in ONE XLA program — the
    multi-chip analog of :func:`fused_lloyd_run`: Σ|x|² hoisted once, a
    ``fori_loop`` of single-pass kernel steps, one psum per step."""
    fn = _sharded_run_fn(
        comm.mesh, comm.axis_name, comm.size, k, int(n_global), int(n_steps), bool(interpret)
    )
    return fn(data, centers)


@functools.lru_cache(maxsize=None)
def _sharded_run_fn(mesh, axis, p, k, n_global, n_steps, interpret):
    step = _sharded_iter_fn(mesh, axis, k, n_global, interpret)

    @jax.jit
    def run(data, centers):
        xsq_sum = _logical_xsq_sum(data, n_global)

        def body(i, carry):
            c = carry[0]
            new_c, inertia, shift = step(data, c, xsq_sum)
            return (new_c, c, inertia, shift)

        acc = jnp.zeros((), jnp.float32)
        c0 = centers.astype(jnp.float32)
        new_c, used, inertia, shift = jax.lax.fori_loop(
            0, n_steps, body, (c0, c0, acc, acc)
        )
        labels = _assign_labels(data, used)[:n_global]
        return new_c, labels, inertia, shift

    return run

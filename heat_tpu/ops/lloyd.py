"""Fused single-pass Lloyd iteration (pallas), in samples-in-lanes layout.

The jnp Lloyd step (`cluster/kmeans.py:_lloyd_iter`) necessarily reads the
(n, f) data from HBM twice per iteration — once for the assignment matmul
``x @ cᵀ`` and once for the update matmul ``onehotᵀ @ x`` — and materializes
the (n, k) one-hot operand for the MXU. At the benchmark shape (10M x 16
f32) the iteration is pure HBM bandwidth, so the floor is set by bytes
moved, not FLOPs.

This kernel streams each sample block into VMEM ONCE and produces everything
the iteration needs in that single pass. Crucially it operates on the
TRANSPOSED operand ``xT (f, n)`` — features in sublanes, samples in lanes:

    score   = |c|² − 2·c @ xb           (k, block)   MXU
    labels  = argmin₀(score)             (1, block)  sublane reduce
    inertia += Σ min₀(score)             scalar accumulator
    onehot  = (labels == iota_k)         (k, block)  VMEM-only
    sumsᵀ  += xb ·ₗ onehot               (f, k)      MXU (lane contraction)
    counts += Σₗ onehot                  (k, 1)      accumulator

Why transposed: TPU vector memory pads the MINOR axis to 128 lanes. In the
natural (block, f) layout a narrow f (the benchmark's f=16) pads 8x — the
kernel was measured on a real v5e moving ~5 GB per iteration against the
jnp path's 1.3 GB, a 0.34x "speedup". With samples in lanes the minor axis
is the long one (no padding, any f), the sublane axis is f (padded to 8),
and every reduction in the kernel is lane-preserving. The one-time
``transpose`` to (f, n) costs one data pass and is hoisted out of the
iteration loop; per-iteration HBM traffic is n·f reads and NOTHING
per-row written (labels are not an iteration output at all — a separate
fused jnp epilogue computes the final assignment once per program, against
the centers of the last iteration, which is the jnp oracle's exact label
convention).

This kernel IS the product path: ``cluster.KMeans.fit`` dispatches here on
TPU (``fused_supported`` / ``fused_sharded_supported``), keeping the jnp
path as the fallback and numerical oracle; bench.py's primary kmeans metric
measures whichever path the product dispatches (``lloyd_path`` in the
record), with the other path alongside (``lloyd_jnp_iters_per_sec`` /
``lloyd_fused_vs_jnp``). :func:`fused_lloyd_iter` is
single-device (its pallas_call has no partitioning spec);
:func:`fused_lloyd_iter_sharded` / :func:`fused_lloyd_run_sharded` are the
multi-chip forms: a shard_map running the kernel per device and merging the
(f, k)/(k, 1)/scalar accumulators with one psum per iteration — the exact
collective budget of the jnp path. In the sharded run the whole fori_loop
lives INSIDE the shard_map so the per-device transpose is paid once per
program, not once per iteration.
"""

from __future__ import annotations

import functools
from typing import Optional

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

__all__ = [
    "fused_lloyd_iter",
    "fused_lloyd_iter_sharded",
    "fused_lloyd_run",
    "fused_lloyd_run_sharded",
    "fused_sharded_supported",
    "fused_supported",
]


def _block_cols(f: int, k: int) -> int:
    """Samples (lanes) per grid step, sized against the scoped-VMEM budget
    on a v5e (16 MB limit). Live vectors per lane: the double-buffered
    (f, block) input plus the (k, block)-shaped score/onehot/min chain —
    all sublane-padded to multiples of 8. Budget ≤ 12 MB leaves headroom
    for the (f, k)/(k, 1) accumulators and c/csq. (An earlier (block, f)
    kernel ignored lane padding and hit the 16 MB scoped limit to within
    1.5 KB; this sizing is measured, not aspirational.)"""
    fp = 8 * ((f + 7) // 8)
    kp = 8 * ((k + 7) // 8)
    per_lane = 4 * (2 * fp + 3 * kp + 8)
    blk = (12 << 20) // per_lane
    return max(1024, min(65536, blk // 128 * 128))


def fused_supported(n: int, f: int, k: int) -> bool:
    """TPU backend, single device (the kernel has no partitioning spec —
    a sharded operand would be gathered), and sublane-safe f/k."""
    try:
        backend_ok = jax.default_backend() in ("tpu", "axon")
        single = len(jax.devices()) == 1
    except Exception:  # pragma: no cover
        return False
    return backend_ok and single and f <= 512 and k <= 128


def fused_sharded_supported(f: int, k: int) -> bool:
    """TPU backend and sublane-safe shapes; device count is irrelevant (the
    shard_map wrapper runs the kernel per device)."""
    try:
        backend_ok = jax.default_backend() in ("tpu", "axon")
    except Exception:  # pragma: no cover
        return False
    return backend_ok and f <= 512 and k <= 128


def _lloyd_kernel(
    xT_ref,
    csq_ref,
    c_ref,
    nvalid_ref,
    sumsT_ref,
    counts_ref,
    inertia_ref,
    *,
    k: int,
    block: int,
):
    """One (f, block) sample block; accumulators live across the whole grid.
    Samples at column index >= nvalid (tail padding: ragged sizes, or a
    device's share of the global padding under the sharded wrapper) are
    masked out of every accumulator. n_valid is a runtime (1, 1) scalar
    operand so each device can carry its own count.

    Every intermediate is 2-D: Mosaic lays a 1-D (block,) value out with a
    replicated sublane and chaining argmin / where / reduce through that
    layout hits "Invalid relayout: non-singleton logical dimension is
    replicated in destination but not in source" (observed on a real v5e;
    benchmarks/TPU_WINDOW_r04.json mosaic_variants passes each construct
    alone — only the 1-D chain fails)."""
    i = pl.program_id(0)

    cols = i * block + jax.lax.broadcasted_iota(jnp.int32, (1, block), 1)
    valid = cols < nvalid_ref[0, 0]  # (1, block) bool

    # Pad-region content is UNSPECIFIED (dndarray.parray contract) — inf/NaN
    # there would poison the accumulators through 0·inf = NaN in the sums
    # contraction, so zero invalid samples rather than relying on
    # multiplicative masking downstream.
    xb = jnp.where(valid, xT_ref[:, :], 0)  # (f, block)

    # (k, block) assignment scores; |x|² omitted (sample-constant for argmin)
    score = csq_ref[:, :] - 2.0 * jnp.dot(
        c_ref[:, :], xb, preferred_element_type=jnp.float32
    )
    kcol = jax.lax.broadcasted_iota(jnp.int32, (k, 1), 0)
    labels = jnp.argmin(score, axis=0, keepdims=True).astype(jnp.int32)  # (1, block)
    onehot = (labels == kcol).astype(xb.dtype) * valid.astype(xb.dtype)  # (k, block)

    @pl.when(i == 0)
    def _init():
        sumsT_ref[:, :] = jnp.zeros_like(sumsT_ref)
        counts_ref[:, :] = jnp.zeros_like(counts_ref)
        inertia_ref[:, :] = jnp.zeros_like(inertia_ref)

    # sumsᵀ (f, k): contract the lane (sample) axes of both operands on the
    # MXU — dot_general, so the (k, block) onehot is never transposed
    sumsT_ref[:, :] += jax.lax.dot_general(
        xb, onehot, (((1,), (1,)), ((), ())), preferred_element_type=jnp.float32
    ).astype(sumsT_ref.dtype)
    # accumulate the count in f32: a bf16 onehot sum saturates at 256
    counts_ref[:, :] += jnp.sum(
        onehot, axis=1, keepdims=True, dtype=counts_ref.dtype
    )
    # where, not multiply: even a finite-but-garbage pad score must not leak,
    # and NaN·0 = NaN would defeat a multiplicative mask
    min2d = jnp.min(score, axis=0, keepdims=True)  # (1, block)
    masked_min = jnp.where(valid, min2d, 0.0)  # (1, block)
    inertia_ref[:, :] += jnp.sum(masked_min, dtype=inertia_ref.dtype)[None, None]


def _prepare(data: jax.Array, block: int) -> jax.Array:
    """(n, f) -> (f, n_pad): transpose to samples-in-lanes and pad the
    sample axis to a block multiple. One data pass; loop-invariant, so XLA
    hoists it out of an enclosing fori_loop.

    bfloat16 stays bfloat16 — the kernel's contractions accumulate in f32
    (``preferred_element_type``) while the streamed operand keeps half the
    HBM footprint, doubling the bandwidth-bound iteration rate. Everything
    else (f64 included: Mosaic cannot lower it) is carried as f32."""
    x = data if data.dtype == jnp.bfloat16 else data.astype(jnp.float32)
    n = x.shape[0]
    n_pad = -(-n // block) * block
    xT = jnp.transpose(x)
    if n_pad != n:
        xT = jnp.pad(xT, ((0, 0), (0, n_pad - n)))
    return xT


def _kernel_call_T(xT, centers, k: int, n_valid, interpret: bool):
    """Invoke the kernel on a prepared (f, n_pad) operand. Returns the raw
    (sumsT, counts, inertia) accumulators — labels are deliberately NOT a
    kernel output (see the module docstring on lane padding)."""
    f, n_pad = xT.shape
    block = _block_cols(f, k)
    assert n_pad % block == 0, (n_pad, block)
    c32 = centers.astype(jnp.float32)
    csq = jnp.sum(c32 * c32, axis=1, keepdims=True)  # (k, 1) — always f32
    # the score dot's operands must share the streamed dtype (bf16 stays
    # bf16 on the MXU; accumulation is f32 via preferred_element_type)
    cx = c32.astype(xT.dtype)
    nv = jnp.reshape(n_valid.astype(jnp.int32), (1, 1))

    return pl.pallas_call(
        functools.partial(_lloyd_kernel, k=k, block=block),
        out_shape=(
            jax.ShapeDtypeStruct((f, k), jnp.float32),
            jax.ShapeDtypeStruct((k, 1), jnp.float32),
            jax.ShapeDtypeStruct((1, 1), jnp.float32),
        ),
        grid=(n_pad // block,),
        in_specs=[
            pl.BlockSpec((f, block), lambda i: (0, i), memory_space=pltpu.VMEM),
            pl.BlockSpec((k, 1), lambda i: (0, 0), memory_space=pltpu.VMEM),
            pl.BlockSpec((k, f), lambda i: (0, 0), memory_space=pltpu.VMEM),
            pl.BlockSpec((1, 1), lambda i: (0, 0), memory_space=pltpu.VMEM),
        ],
        out_specs=(
            pl.BlockSpec((f, k), lambda i: (0, 0), memory_space=pltpu.VMEM),
            pl.BlockSpec((k, 1), lambda i: (0, 0), memory_space=pltpu.VMEM),
            pl.BlockSpec((1, 1), lambda i: (0, 0), memory_space=pltpu.VMEM),
        ),
        interpret=interpret,
    )(xT, csq, cx, nv)


def _kernel_call(data, centers, k: int, n_valid, interpret: bool):
    """Pad, transpose, and invoke the kernel on one device's rows — the
    (n, f)-in convenience form (single calls and tests; iteration loops use
    :func:`_prepare` + :func:`_kernel_call_T` so the transpose hoists)."""
    xT = _prepare(data, _block_cols(data.shape[1], k))
    return _kernel_call_T(xT, centers, k, n_valid, interpret)


def _assign_labels(data: jax.Array, centers: jax.Array) -> jax.Array:
    """The assignment step alone, as one fused XLA pass: labels w.r.t.
    ``centers``. Runs ONCE per program as the label epilogue — per-row labels
    are not a kernel output (module docstring).

    The score is computed in the STREAMED dtype: for bfloat16 data the dot's
    operands stay bf16 with f32 accumulation, exactly like the kernel's
    score contraction — an all-f32 epilogue would disagree with the bf16
    argmin that produced the kernel's sums/counts for boundary samples, so
    ``labels_`` could contradict ``cluster_centers_`` (advisor r04#2)."""
    c32 = centers.astype(jnp.float32)
    csq = jnp.sum(c32 * c32, axis=1)  # always from the UNQUANTIZED centers,
    # exactly like _kernel_call_T's csq operand
    if data.dtype == jnp.bfloat16:
        x, c = data, c32.astype(jnp.bfloat16)
    else:
        x, c = data.astype(jnp.float32), c32
    dot = jax.lax.dot_general(
        x, c, (((1,), (1,)), ((), ())), preferred_element_type=jnp.float32
    )
    score = csq[None, :] - 2.0 * dot
    return jnp.argmin(score, axis=1).astype(jnp.int32)


@functools.partial(jax.jit, static_argnames=("k", "interpret"))
def fused_lloyd_iter(
    data: jax.Array, centers: jax.Array, k: int, xsq_sum=None, interpret: bool = False
):
    """One Lloyd iteration in a single accumulator pass (+ label epilogue).

    Returns ``(new_centers, labels, inertia, shift)`` with the same contract
    as ``cluster.kmeans._lloyd_iter`` (inertia includes the Σ|x|² term;
    labels are the assignment against the INPUT centers).
    ``xsq_sum`` is the loop-invariant Σ|x|²; pass it from outside an
    iteration loop, or it is computed here (costing the one extra data read
    the kernel exists to avoid).

    Cost note (advisor r04#4): every call pays the ``_assign_labels``
    epilogue — a FULL extra data pass — plus the Σ|x|² pass when ``xsq_sum``
    is not supplied, so a Python loop over single calls reads the data ~3x
    per iteration. Iteration loops should use :func:`fused_lloyd_run`
    (labels once per N-step program) with :func:`prepare_run_operands`
    hoisting the transpose/Σ|x|² across chunks — that combination is the
    advertised one-read-per-iteration path.
    """
    n = data.shape[0]
    sumsT, counts, inertia = _kernel_call(
        data, centers, k, jnp.asarray(n, jnp.int32), interpret
    )
    if xsq_sum is None:
        x32 = data.astype(jnp.float32)
        xsq_sum = jnp.sum(x32 * x32)
    new_centers, inertia_full, shift = _finalize(
        sumsT, counts, inertia, centers, xsq_sum
    )
    return new_centers, _assign_labels(data, centers), inertia_full, shift


def _finalize(sumsT, counts, inertia, centers, xsq_sum):
    """Shared epilogue: centroid update (empty clusters keep their center),
    inertia restoration (+Σ|x|²), and the convergence shift. One body for
    the single-device and sharded paths so their numerics cannot drift."""
    counts = counts[:, 0]  # (k,)
    sums = sumsT.T  # (k, f) — tiny
    new_centers = jnp.where(
        counts[:, None] > 0,
        sums / jnp.maximum(counts[:, None], 1.0),
        centers.astype(jnp.float32),
    ).astype(centers.dtype)
    inertia_full = jnp.maximum(inertia[0, 0] + xsq_sum, 0.0)
    shift = jnp.sum((new_centers - centers).astype(jnp.float32) ** 2)
    return new_centers, inertia_full, shift


def prepare_run_operands(data: jax.Array, k: int):
    """(xT, xsq_sum) for :func:`fused_lloyd_run` — callers driving MANY run
    chunks over the same operand (KMeans.fit's convergence loop) compute
    these ONCE and pass them in, instead of paying the transpose + Σ|x|²
    data passes on every chunk."""
    x32 = data.astype(jnp.float32)
    return (
        _prepare(data, _block_cols(data.shape[1], k)),
        jnp.sum(x32 * x32),
    )


_prepare_run_operands = functools.partial(jax.jit, static_argnames="k")(
    prepare_run_operands
)


@functools.partial(jax.jit, static_argnames=("k", "n_steps", "interpret"))
def fused_lloyd_run(
    data: jax.Array,
    centers: jax.Array,
    k: int,
    n_steps: int,
    interpret: bool = False,
    xT: Optional[jax.Array] = None,
    xsq_sum: Optional[jax.Array] = None,
):
    """``n_steps`` fused iterations in one XLA program (the pallas analog of
    ``cluster.kmeans._lloyd_run``): Σ|x|² and the samples-in-lanes transpose
    hoisted (within the program — pass ``xT``/``xsq_sum`` from
    :func:`prepare_run_operands` to hoist them across chunked calls too),
    one kernel pass per step, labels from ONE epilogue pass against the last
    iteration's input centers (the jnp oracle's exact label convention)."""
    if xsq_sum is None:
        x32 = data.astype(jnp.float32)
        xsq_sum = jnp.sum(x32 * x32)
    if xT is None:
        xT = _prepare(data, _block_cols(data.shape[1], k))
    n_valid = jnp.asarray(data.shape[0], jnp.int32)

    def body(i, carry):
        centers, _, _, _ = carry
        sumsT, counts, inertia = _kernel_call_T(xT, centers, k, n_valid, interpret)
        new_centers, inertia_full, shift = _finalize(
            sumsT, counts, inertia, centers, xsq_sum
        )
        return (new_centers, centers, inertia_full, shift)

    acc = jnp.zeros((), jnp.float32)
    centers, used, inertia, shift = jax.lax.fori_loop(
        0, n_steps, body, (centers, centers, acc, acc)
    )
    return centers, _assign_labels(data, used), inertia, shift


def fused_lloyd_iter_sharded(
    data: jax.Array,
    centers: jax.Array,
    k: int,
    comm,
    n_global: int,
    xsq_sum=None,
    interpret: bool = False,
):
    """One fused Lloyd iteration over a row-sharded operand.

    ``data`` is the PHYSICAL payload (``DNDarray.parray``): row count a
    multiple of the mesh size, suffix-padded when the logical ``n_global``
    is ragged. Each device runs the single-pass kernel on its own block —
    masking its share of the global padding — and the (f, k)/(k, 1)/scalar
    accumulators merge with one ``psum``. Labels come from the shared jnp
    epilogue on the row-sharded global view (no collectives: the matmul
    against replicated centers and the argmin are row-local), sliced to the
    logical length ``n_global``.

    Same return contract as :func:`fused_lloyd_iter`. The whole iteration
    (shard_map + epilogue) is jitted, cached per (mesh, k, shapes).
    """
    fn = _sharded_fn(comm.mesh, comm.axis_name, comm.size, k, int(n_global), bool(interpret))
    return fn(data, centers, xsq_sum)


def _sharded_iter_fn(mesh, axis, k, n_global, interpret):
    """Traced (data, centers, xsq_sum) -> (new_centers, inertia, shift) over
    a row-sharded physical payload (single iteration; the fused-run form
    keeps its loop inside the shard_map instead — see _sharded_run_fn)."""
    from jax.sharding import PartitionSpec as P

    def device_step(xl, c):
        local_rows = xl.shape[0]
        idx = jax.lax.axis_index(axis)
        local_valid = jnp.clip(n_global - idx * local_rows, 0, local_rows)
        sums, counts, inertia = _kernel_call(xl, c, k, local_valid, interpret)
        sums = jax.lax.psum(sums, axis)
        counts = jax.lax.psum(counts, axis)
        inertia = jax.lax.psum(inertia, axis)
        return sums, counts, inertia

    def step(data, centers, xsq_sum):
        sums, counts, inertia = jax.shard_map(
            device_step,
            mesh=mesh,
            in_specs=(P(axis, None), P()),
            out_specs=(P(), P(), P()),
            check_vma=False,  # pallas_call outputs carry no vma annotation
        )(data, centers)
        return _finalize(sums, counts, inertia, centers, xsq_sum)

    return step


def _logical_xsq_sum(data, n_global):
    # Σ|x|² over the LOGICAL rows only: the physical pad region's content is
    # unspecified (dndarray.parray contract) — never fold it into the inertia
    x32 = data[:n_global].astype(jnp.float32)
    return jnp.sum(x32 * x32)


_sharded_xsq = functools.partial(jax.jit, static_argnames="n_global")(_logical_xsq_sum)
"""Chunk-loop hoist of the sharded Σ|x|² (KMeans.fit computes it once)."""


@functools.lru_cache(maxsize=None)
def _sharded_fn(mesh, axis, p, k, n_global, interpret):
    """Jitted sharded iteration, cached per static config (the
    attention.py:_ring_attention_fn closure-cache pattern — comm objects are
    unhashable, their mesh/axis are)."""
    step = _sharded_iter_fn(mesh, axis, k, n_global, interpret)

    @jax.jit
    def run(data, centers, xsq_sum):
        if xsq_sum is None:
            xsq_sum = _logical_xsq_sum(data, n_global)
        new_centers, inertia, shift = step(data, centers, xsq_sum)
        labels = _assign_labels(data, centers)[:n_global]
        return new_centers, labels, inertia, shift

    return run


def fused_lloyd_run_sharded(
    data: jax.Array,
    centers: jax.Array,
    k: int,
    comm,
    n_global: int,
    n_steps: int,
    interpret: bool = False,
    xsq_sum: Optional[jax.Array] = None,
):
    """``n_steps`` fused sharded iterations in ONE XLA program — the
    multi-chip analog of :func:`fused_lloyd_run`: Σ|x|² hoisted once (pass
    ``xsq_sum`` to hoist it across chunked calls too; the per-device
    transpose lives inside the shard_map and is paid once per program), the
    fori_loop of single-pass kernel steps INSIDE the shard_map, one psum
    per step."""
    fn = _sharded_run_fn(
        comm.mesh, comm.axis_name, comm.size, k, int(n_global), int(n_steps), bool(interpret)
    )
    return fn(data, centers, xsq_sum)


@functools.lru_cache(maxsize=None)
def _sharded_run_fn(mesh, axis, p, k, n_global, n_steps, interpret):
    from jax.sharding import PartitionSpec as P

    def device_run(xl, c0, xsq_sum):
        local_rows = xl.shape[0]
        idx = jax.lax.axis_index(axis)
        local_valid = jnp.clip(n_global - idx * local_rows, 0, local_rows)
        f = xl.shape[1]
        xT = _prepare(xl, _block_cols(f, k))  # once per program, per device

        def body(i, carry):
            c, _, _, _ = carry
            sumsT, counts, inertia = _kernel_call_T(xT, c, k, local_valid, interpret)
            sumsT = jax.lax.psum(sumsT, axis)
            counts = jax.lax.psum(counts, axis)
            inertia = jax.lax.psum(inertia, axis)
            new_c, inertia_full, shift = _finalize(sumsT, counts, inertia, c, xsq_sum)
            return (new_c, c, inertia_full, shift)

        acc = jnp.zeros((), jnp.float32)
        c0 = c0.astype(jnp.float32)
        return jax.lax.fori_loop(0, n_steps, body, (c0, c0, acc, acc))

    @jax.jit
    def run(data, centers, xsq_sum=None):
        if xsq_sum is None:
            xsq_sum = _logical_xsq_sum(data, n_global)
        new_c, used, inertia, shift = jax.shard_map(
            device_run,
            mesh=mesh,
            in_specs=(P(axis, None), P(), P()),
            out_specs=(P(), P(), P(), P()),
            check_vma=False,  # pallas_call outputs carry no vma annotation
        )(data, centers, xsq_sum)
        labels = _assign_labels(data, used)[:n_global]
        return new_c.astype(centers.dtype), labels, inertia, shift

    return run

"""Fused pairwise-distance pallas kernel.

The exact (non-quadratic-expansion) metrics in reference
heat/spatial/distance.py:16-37 (L2) and :95-115 (L1) are computed there as a
broadcast ``|x[:,None,:] - y[None,:,:]|`` reduce — an O(n·m·f) intermediate
that is pure HBM traffic. On TPU that intermediate never needs to exist: this
kernel tiles the (n, m) output over a pallas grid, streams x/y row blocks
into VMEM once per tile, and reduces the feature axis on-chip, so HBM traffic
is O(n·m + (n+m)·f) — the lower bound — by construction.

Honest perf note (measured, v5e-1): XLA's own fusion of the broadcast
expression also avoids materializing the intermediate and currently beats
this kernel ~2-3x on VPU throughput for f ∈ [64, 256], so the default
``spatial.cdist`` path stays on the XLA expression ("don't hand-schedule
what the compiler already does"). The kernel is kept as (a) the template for
fused-tile pairwise patterns (ring attention tiles, flash-style reductions)
and (b) a guaranteed-VMEM-footprint variant whose memory behavior is
shape-predictable where XLA's fusion choices are not.

Layout: the feature axis is the TPU lane dimension (padded to 128), so the
per-step broadcast ``(ROWS, TN, F)`` lives entirely in VMEM and the feature
reduction is a lane reduction — no dynamic lane slicing (Mosaic requires
lane indices to be 128-aligned).

Numerics match the reference's exact path (difference first, then square/abs)
— NOT the quadratic expansion |x|²+|y|²−2x·yᵀ, which loses precision when
x≈y. This is the "exact but fast" option the reference cannot offer.
"""

from __future__ import annotations

import functools
from typing import Optional

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

__all__ = ["pairwise_distance", "pallas_supported"]

_TM = 256  # output tile rows (x block)
_TN = 256  # output tile cols (y block)
_ROWS = 8  # x rows reduced per VPU step (one f32 sublane tile)
_LANE = 128  # feature padding quantum (lane width)
_MAX_F = 512  # above this the (ROWS, TN, F) step intermediate pressures VMEM


def pallas_supported(f: int) -> bool:
    """Whether the fused kernel can run here: TPU backend and a feature count
    whose VMEM footprint fits (step intermediate ROWS·TN·F·4B ≤ 4 MB)."""
    try:
        return jax.default_backend() in ("tpu", "axon") and f <= _MAX_F
    except Exception:  # pragma: no cover - backend probing must never raise
        return False


def _pairwise_kernel(x_ref, y_ref, o_ref, *, p: int, post_sqrt: bool):
    """One (TM, TN) output tile.

    x_ref: (TM, F) block, y_ref: (TN, F) block, o_ref: (TM, TN). F is padded
    to the lane width outside; zero features contribute nothing to L1/L2.
    """
    y = y_ref[:, :]  # (TN, F), resident for the whole tile

    def body(i, _):
        r = pl.multiple_of(i * _ROWS, _ROWS)
        xb = x_ref[pl.ds(r, _ROWS), :]  # (ROWS, F)
        diff = xb[:, None, :] - y[None, :, :]  # (ROWS, TN, F)
        if p == 1:
            part = jnp.sum(jnp.abs(diff), axis=-1)
        else:
            part = jnp.sum(diff * diff, axis=-1)
        o_ref[pl.ds(r, _ROWS), :] = jnp.sqrt(part) if post_sqrt else part
        return 0

    jax.lax.fori_loop(0, o_ref.shape[0] // _ROWS, body, 0)


@functools.partial(jax.jit, static_argnames=("p", "post", "interpret"))
def _pairwise_padded(x: jax.Array, y: jax.Array, p: int, post: bool, interpret: bool = False) -> jax.Array:
    """Grid-tiled pallas call over feature-padded, row-padded operands."""
    n, f = x.shape
    m = y.shape[0]
    kernel = functools.partial(_pairwise_kernel, p=p, post_sqrt=post)
    return pl.pallas_call(
        kernel,
        out_shape=jax.ShapeDtypeStruct((n, m), x.dtype),
        grid=(n // _TM, m // _TN),
        in_specs=[
            pl.BlockSpec((_TM, f), lambda i, j: (i, 0), memory_space=pltpu.VMEM),
            pl.BlockSpec((_TN, f), lambda i, j: (j, 0), memory_space=pltpu.VMEM),
        ],
        out_specs=pl.BlockSpec((_TM, _TN), lambda i, j: (i, j), memory_space=pltpu.VMEM),
        interpret=interpret,
    )(x, y)


def pairwise_distance(
    x: jax.Array,
    y: Optional[jax.Array] = None,
    p: int = 2,
    squared: bool = False,
    interpret: bool = False,
) -> jax.Array:
    """Exact pairwise Lp distance matrix ``(n, m)`` with fused feature
    reduction. ``p`` ∈ {1, 2}; ``squared=True`` skips the final sqrt (L2 only).

    Pads rows to the 256-tile and features to the lane width, then slices the
    result — zero-padding features is exact for both metrics; padded rows are
    discarded.
    """
    if y is None:
        y = x
    if p not in (1, 2):
        raise ValueError(f"p must be 1 or 2, got {p}")
    if x.ndim != 2 or y.ndim != 2:
        raise ValueError(f"x and y must be 2D, got {x.ndim}D and {y.ndim}D")
    if x.shape[1] != y.shape[1]:
        raise ValueError(f"feature counts differ: {x.shape[1]} != {y.shape[1]}")
    n, f = x.shape
    m = y.shape[0]
    if f > _MAX_F:
        raise ValueError(
            f"f={f} exceeds the kernel's VMEM budget (max {_MAX_F}); "
            "use the XLA broadcast expression for wide features"
        )
    dtype = jnp.promote_types(x.dtype, jnp.float32)
    x = x.astype(dtype)
    y = y.astype(dtype)

    f_pad = -f % _LANE
    n_pad = -n % _TM
    m_pad = -m % _TN
    if f_pad:
        x = jnp.pad(x, ((0, 0), (0, f_pad)))
        y = jnp.pad(y, ((0, 0), (0, f_pad)))
    if n_pad:
        x = jnp.pad(x, ((0, n_pad), (0, 0)))
    if m_pad:
        y = jnp.pad(y, ((0, m_pad), (0, 0)))

    out = _pairwise_padded(x, y, p, post=(p == 2 and not squared), interpret=interpret)
    if n_pad or m_pad:
        out = out[:n, :m]
    return out

"""K-nearest-neighbors classifier (reference:
heat/classification/kneighborsclassifier.py:62-135).

Pipeline identical to the reference: cdist to the training set → topk of the
negated distances → one-hot vote → argmax. All three stages are sharded XLA
ops (the reference's distributed topk merge op is manipulations.topk here).
"""

from __future__ import annotations

from typing import Optional

import jax.numpy as jnp

from ..core import factories, types
from ..core.base import BaseEstimator, ClassificationMixin
from ..core.dndarray import DNDarray, _ensure_split
from ..spatial import distance

__all__ = ["KNeighborsClassifier"]


class KNeighborsClassifier(ClassificationMixin, BaseEstimator):
    """KNN classification (reference kneighborsclassifier.py:14-61).

    Parameters
    ----------
    n_neighbors : int
        Number of neighbors considered in the vote.
    """

    def __init__(self, n_neighbors: int = 5):
        self.n_neighbors = n_neighbors
        self.x = None
        self.y = None
        self.classes = None

    def fit(self, x: DNDarray, y: DNDarray) -> "KNeighborsClassifier":
        """Memorize the training set (reference kneighborsclassifier.py:62-88).

        ``y`` may be integer labels (n,) or one-hot (n, c).
        """
        if not isinstance(x, DNDarray) or not isinstance(y, DNDarray):
            raise TypeError("x and y need to be DNDarrays")
        if x.shape[0] != y.shape[0]:
            raise ValueError("Number of samples and labels needs to be the same")
        self.x = x
        if y.ndim == 1:
            import jax.numpy as _jnp

            classes = _jnp.unique(y.larray)
            self.classes = classes
            onehot = (y.larray[:, None] == classes[None, :]).astype(_jnp.float32)
            self.y = onehot
        elif y.ndim == 2:
            self.classes = None
            self.y = y.larray.astype(jnp.float32)
        else:
            raise ValueError(f"labels need to be 1D or 2D, but were {y.ndim}D")
        return self

    def predict(self, x: DNDarray) -> DNDarray:
        """Label prediction (reference kneighborsclassifier.py:89-135)."""
        if self.x is None:
            raise RuntimeError("fit needs to be called before predict")
        if not isinstance(x, DNDarray):
            raise TypeError("x needs to be a DNDarray")
        d = distance.cdist(x, self.x, quadratic_expansion=True)  # (m, n)
        k = self.n_neighbors
        # indices of the k smallest distances (top-k, not a full sort)
        import jax

        _, idx = jax.lax.top_k(-d.larray, k)  # (m, k)
        votes = self.y[idx]  # (m, k, c)
        counts = jnp.sum(votes, axis=1)  # (m, c)
        winner = jnp.argmax(counts, axis=1)  # (m,)
        if self.classes is not None:
            labels = self.classes[winner]
        else:
            labels = winner.astype(jnp.int32)
        labels = _ensure_split(labels, x.split, x.comm)
        return DNDarray(
            labels, tuple(labels.shape), types.canonical_heat_type(labels.dtype), x.split, x.device, x.comm
        )

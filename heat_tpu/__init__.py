"""heat_tpu — a TPU-native distributed n-dimensional tensor framework.

Brand-new implementation of the capabilities of Heat (Helmholtz Analytics
Toolkit): NumPy-like distributed arrays with a single ``split`` axis, realized
as globally-sharded ``jax.Array``s over a device mesh; XLA/GSPMD inserts the
collectives the reference hand-codes over MPI. See SURVEY.md for the blueprint.
"""

from .core import *
from .core import linalg
from .core import (
    arithmetics,
    base,
    communication,
    complex_math,
    constants,
    devices,
    exponential,
    factories,
    logical,
    memory,
    printing,
    relational,
    rounding,
    sanitation,
    stride_tricks,
    trigonometrics,
    types,
    version,
)
from .core.version import __version__


def __getattr__(name):
    # Lazy singletons: constructing them initializes the JAX backend, which
    # must not happen at import time (users/tests may flip platforms first).
    if name in ("MPI_WORLD", "MESH_WORLD"):
        return communication.get_comm()
    if name in ("MPI_SELF", "MESH_SELF"):
        communication.get_comm()
        return communication.MESH_SELF
    raise AttributeError(f"module {__name__!r} has no attribute {name!r}")

"""heat_tpu — a TPU-native distributed n-dimensional tensor framework.

Brand-new implementation of the capabilities of Heat (Helmholtz Analytics
Toolkit): NumPy-like distributed arrays with a single ``split`` axis, realized
as globally-sharded ``jax.Array``s over a device mesh; XLA/GSPMD inserts the
collectives the reference hand-codes over MPI. See SURVEY.md for the blueprint.
"""

from .core import *
from .core import linalg, random
from . import classification, cluster, datasets, graph, naive_bayes, nn, ops, optim, regression, spatial, utils
from .utils import checkpoint  # ht.checkpoint — the verified sharded checkpoint subsystem
from .core import (
    arithmetics,
    autoscale,
    base,
    communication,
    complex_math,
    constants,
    devices,
    elastic,
    exponential,
    factories,
    health_runtime,
    indexing,
    io,
    logical,
    manipulations,
    memledger,
    memory,
    numlens,
    opsplane,
    printing,
    relational,
    resilience,
    rounding,
    sanitation,
    serving,
    signal,
    statistics,
    stride_tricks,
    telemetry,
    tiling,
    tracelens,
    trigonometrics,
    types,
    version,
)
from .core.version import __version__

#: the runtime health layer's short name: ``ht.flight.dump_flight()``,
#: ``ht.flight.watch(...)``, ``ht.flight.health_block()``
flight = health_runtime


def _bind_dndarray_methods():
    """Bind the operator library onto DNDarray as methods — the reference
    exposes most library functions as both ``ht.fn(x)`` and ``x.fn()``
    (reference dndarray.py method defs scattered through the modules)."""
    from .core.dndarray import DNDarray as _D

    _method_sources = {
        arithmetics: [
            "add", "sub", "mul", "div", "pow", "fmod", "mod", "cumsum", "cumprod",
            "prod", "sum", "nansum", "nanprod", "diff",
        ],
        rounding: ["abs", "ceil", "clip", "fabs", "floor", "modf", "round", "trunc", "sign", "sgn"],
        exponential: ["exp", "expm1", "exp2", "log", "log2", "log10", "log1p", "sqrt", "square"],
        trigonometrics: [
            "sin", "cos", "tan", "sinh", "cosh", "tanh", "arcsin", "arccos", "arctan",
            "arcsinh", "arccosh", "arctanh",
        ],
        logical: ["all", "any", "allclose", "isclose"],
        statistics: [
            "argmax", "argmin", "average", "max", "mean", "median", "min", "percentile",
            "std", "var", "kurtosis", "skew",
        ],
        manipulations: [
            "expand_dims", "flatten", "ravel", "reshape", "resplit", "squeeze", "unique",
            "flip", "roll", "repeat", "tile", "moveaxis", "swapaxes", "collect",
            "balance", "redistribute", "rot90",
        ],
        complex_math: ["conj"],
        indexing: ["nonzero"],
        memory: ["copy"],
        io: ["save", "save_hdf5", "save_netcdf", "save_csv"],
    }
    for module, names in _method_sources.items():
        for name in names:
            if not hasattr(_D, name):
                setattr(_D, name, getattr(module, name))
    _D.transpose = linalg.transpose
    _D.tril = linalg.tril
    _D.triu = linalg.triu
    _D.dot = linalg.dot
    _D.qr = linalg.qr


_bind_dndarray_methods()
del _bind_dndarray_methods


def __getattr__(name):
    # Lazy singletons: constructing them initializes the JAX backend, which
    # must not happen at import time (users/tests may flip platforms first).
    if name in ("MPI_WORLD", "MESH_WORLD"):
        return communication.get_comm()
    if name in ("MPI_SELF", "MESH_SELF"):
        communication.get_comm()
        return communication.MESH_SELF
    raise AttributeError(f"module {__name__!r} has no attribute {name!r}")

"""Gaussian naive Bayes (reference: heat/naive_bayes/gaussianNB.py).

Streaming ``partial_fit`` with incremental mean/variance merging
(reference gaussianNB.py:131-199) and joint log-likelihood classification
with a distributed logsumexp (:391-479). The merge formulas are the
reference's (Chan et al.); the reductions they feed on are sharded psums.
"""

from __future__ import annotations

from typing import Optional, Tuple

import jax.numpy as jnp
import numpy as np

from ..core import factories, types
from ..core.base import BaseEstimator, ClassificationMixin
from ..core.dndarray import DNDarray, _ensure_split

__all__ = ["GaussianNB"]


class GaussianNB(ClassificationMixin, BaseEstimator):
    """Gaussian naive Bayes classifier (reference gaussianNB.py:17-130).

    Parameters
    ----------
    priors : DNDarray, optional
        Class priors; inferred from data if None.
    var_smoothing : float
        Ridge added to variances for stability.
    """

    def __init__(self, priors: Optional[DNDarray] = None, var_smoothing: float = 1e-9):
        self.priors = priors
        self.var_smoothing = var_smoothing
        self.classes_ = None
        self.theta_ = None
        self.var_ = None
        self.class_count_ = None
        self.class_prior_ = None
        self.epsilon_ = None

    @property
    def sigma_(self):
        """Per-class feature variances — the reference's name for ``var_``
        (reference gaussianNB.py:38)."""
        return self.var_

    # ------------------------------------------------------------------
    @staticmethod
    def _update_mean_variance(n_past, mu, var, X, sample_weight=None):
        """Chan/Golub/LeVeque incremental moment merge, weighted when
        ``sample_weight`` is given (reference gaussianNB.py:200-260)."""
        if X.shape[0] == 0:
            return n_past, mu, var
        if sample_weight is not None:
            w = jnp.asarray(sample_weight, dtype=X.dtype)
            n_new = float(jnp.sum(w))
            if n_new == 0:
                return n_past, mu, var
            new_mu = jnp.average(X, axis=0, weights=w)
            new_var = jnp.average((X - new_mu) ** 2, axis=0, weights=w)
        else:
            n_new = X.shape[0]
            new_mu = jnp.mean(X, axis=0)
            new_var = jnp.var(X, axis=0)
        if n_past == 0:
            return n_new, new_mu, new_var
        n_total = n_past + n_new
        total_mu = (n_new * new_mu + n_past * mu) / n_total
        old_ssd = n_past * var
        new_ssd = n_new * new_var
        total_ssd = old_ssd + new_ssd + (n_new * n_past / n_total) * (mu - new_mu) ** 2
        return n_total, total_mu, total_ssd / n_total

    def fit(self, x: DNDarray, y: DNDarray, sample_weight=None) -> "GaussianNB":
        """Fit from scratch (reference gaussianNB.py:131-160)."""
        self.classes_ = None
        self.theta_ = None
        return self.partial_fit(x, y, classes=None, sample_weight=sample_weight)

    def partial_fit(
        self, x: DNDarray, y: DNDarray, classes: Optional[DNDarray] = None, sample_weight=None
    ) -> "GaussianNB":
        """Incremental fit on a batch (reference gaussianNB.py:161-199)."""
        if not isinstance(x, DNDarray) or not isinstance(y, DNDarray):
            raise ValueError("x and y must be DNDarrays")
        if x.ndim != 2:
            raise ValueError(f"expected x to be 2D, got {x.ndim}D")
        xl = x.larray.astype(jnp.float32)
        yl = y.larray.reshape(-1)
        if xl.shape[0] != yl.shape[0]:
            raise ValueError(
                f"y.shape[0] must match number of samples {xl.shape[0]}, got {yl.shape[0]}"
            )

        first_call = self.theta_ is None
        if first_call:
            if classes is not None:
                cls = jnp.asarray(
                    classes.larray if isinstance(classes, DNDarray) else classes
                )
            else:
                cls = jnp.unique(yl)
            self.classes_ = cls
            n_features = xl.shape[1]
            n_classes = cls.shape[0]
            self.theta_ = jnp.zeros((n_classes, n_features), jnp.float32)
            self.var_ = jnp.zeros((n_classes, n_features), jnp.float32)
            self.class_count_ = jnp.zeros((n_classes,), jnp.float32)
        cls = self.classes_

        # the variance ridge tracks the data scale (reference gaussianNB.py:166-171)
        self.epsilon_ = self.var_smoothing * float(jnp.var(xl, axis=0).max())
        if not first_call:
            self.var_ = self.var_ - self.epsilon_

        if sample_weight is not None:
            sw = jnp.asarray(
                sample_weight.larray if isinstance(sample_weight, DNDarray) else sample_weight
            ).reshape(-1)
        else:
            sw = None
        theta, var, counts = [], [], []
        for i in range(cls.shape[0]):
            mask = yl == cls[i]
            Xi = xl[mask]
            wi = sw[mask] if sw is not None else None
            n_i, mu, v = self._update_mean_variance(
                float(self.class_count_[i]), self.theta_[i], self.var_[i], Xi, sample_weight=wi
            )
            theta.append(mu)
            var.append(v)
            counts.append(jnp.asarray(n_i, jnp.float32))
        self.theta_ = jnp.stack(theta)
        self.var_ = jnp.stack(var) + self.epsilon_
        self.class_count_ = jnp.stack(counts)

        if self.priors is not None:
            priors = jnp.asarray(
                self.priors.larray if isinstance(self.priors, DNDarray) else self.priors
            )
            if priors.shape[0] != cls.shape[0]:
                raise ValueError("Number of priors must match number of classes.")
            if abs(float(jnp.sum(priors)) - 1.0) > 1e-6:
                raise ValueError("The sum of the priors should be 1.")
            if bool(jnp.any(priors < 0)):
                raise ValueError("Priors must be non-negative.")
            self.class_prior_ = priors
        else:
            self.class_prior_ = self.class_count_ / jnp.sum(self.class_count_)
        return self

    # ------------------------------------------------------------------
    def _joint_log_likelihood(self, xl: jnp.ndarray) -> jnp.ndarray:
        """Per-class joint log likelihood (reference gaussianNB.py:391-430)."""
        jll = []
        for i in range(self.classes_.shape[0]):
            prior = jnp.log(self.class_prior_[i])
            n_ij = -0.5 * jnp.sum(jnp.log(2.0 * jnp.pi * self.var_[i]))
            n_ij = n_ij - 0.5 * jnp.sum(((xl - self.theta_[i]) ** 2) / self.var_[i], axis=1)
            jll.append(prior + n_ij)
        return jnp.stack(jll, axis=1)  # (n, c)

    def predict(self, x: DNDarray) -> DNDarray:
        """Most probable class per sample (reference gaussianNB.py:431-450)."""
        self._check_is_fitted()
        xl = x.larray.astype(jnp.float32)
        jll = self._joint_log_likelihood(xl)
        labels = self.classes_[jnp.argmax(jll, axis=1)]
        labels = _ensure_split(labels, x.split, x.comm)
        return DNDarray(
            labels, tuple(labels.shape), types.canonical_heat_type(labels.dtype), x.split, x.device, x.comm
        )

    def predict_log_proba(self, x: DNDarray) -> DNDarray:
        """Normalized log probabilities via logsumexp (reference gaussianNB.py:451-479)."""
        self._check_is_fitted()
        xl = x.larray.astype(jnp.float32)
        jll = self._joint_log_likelihood(xl)
        import jax

        log_prob = jll - jax.scipy.special.logsumexp(jll, axis=1, keepdims=True)
        log_prob = _ensure_split(log_prob, x.split, x.comm)
        return DNDarray(
            log_prob, tuple(log_prob.shape), types.canonical_heat_type(log_prob.dtype), x.split, x.device, x.comm
        )

    def predict_proba(self, x: DNDarray) -> DNDarray:
        """Class probabilities (reference gaussianNB.py:480-500)."""
        lp = self.predict_log_proba(x)
        arr = jnp.exp(lp.larray)
        return DNDarray(
            arr, tuple(arr.shape), types.canonical_heat_type(arr.dtype), lp.split, lp.device, lp.comm
        )

    def _check_is_fitted(self):
        if self.theta_ is None:
            raise RuntimeError("fit needs to be called before predict")

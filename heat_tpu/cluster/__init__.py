"""Distributed clustering (reference: heat/cluster/__init__.py)."""

from .kmeans import *
from .kmedians import *
from .kmedoids import *
from .spectral import *

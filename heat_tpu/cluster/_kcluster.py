"""Shared k-clustering base (reference: heat/cluster/_kcluster.py).

Centroid initialization follows the reference: ``"random"`` samples k rows
(the reference Bcasts each owning rank's row, _kcluster.py:100-129 — global
indexing makes the Bcast implicit), ``"probability_based"`` is kmeans++ with
cdist-min sampling (:142-187). Assignment is metric + argmin (:196-209),
compiled as one XLA kernel.
"""

from __future__ import annotations

import functools
from typing import Callable, Optional, Union

import jax
import jax.numpy as jnp
import numpy as np

from ..core import factories, types
from ..core import random as ht_random
from ..core.base import BaseEstimator, ClusteringMixin
from ..core.dndarray import DNDarray, _ensure_split

__all__ = ["_KCluster"]


def _kmeanspp_fixed(key: jax.Array, data: jax.Array, k: int, metric) -> jax.Array:
    """Fixed-shape kmeans++ over one in-memory block, traceable under jit:
    the centers buffer is (k, f) with unfilled rows masked out of the
    min-distance via the step index (no data-dependent shapes, so the whole
    sampling loop is one fori_loop on device)."""
    n, f = data.shape
    key, sub0 = jax.random.split(key)
    first = jax.random.randint(sub0, (), 0, n)
    centers0 = jnp.zeros((k, f), data.dtype).at[0].set(data[first])

    def body(i, carry):
        centers, key = carry
        key, sub = jax.random.split(key)
        d = metric(data, centers)  # (n, k)
        valid = jnp.arange(k)[None, :] < i
        dmin = jnp.min(jnp.where(valid, d, jnp.inf), axis=1)
        total = jnp.sum(dmin)
        prob = jnp.where(total > 0, dmin / jnp.maximum(total, 1e-30), 1.0 / n)
        r = jax.random.uniform(sub, dtype=prob.dtype)
        nxt = jnp.clip(jnp.searchsorted(jnp.cumsum(prob), r), 0, n - 1)
        return centers.at[i].set(data[nxt]), key

    centers, _ = jax.lax.fori_loop(1, k, body, (centers0, key))
    return centers


@functools.lru_cache(maxsize=64)
def _batchparallel_kernel(axis_name: str, k: int, metric):
    """One stable batch-parallel-init kernel per (mesh axis, k, metric) —
    the PRNG key is a kernel OPERAND, not a closure constant, so re-inits
    with fresh seeds reuse the same compiled program (H004 contract)."""

    def kernel(block, key):
        idx = jax.lax.axis_index(axis_name)
        local = _kmeanspp_fixed(jax.random.fold_in(key, idx), block, k, metric)
        cands = jax.lax.all_gather(local, axis_name, tiled=True)  # (p*k, f)
        return _kmeanspp_fixed(key, cands, k, metric)

    kernel.__name__ = f"batchparallel_init_k{k}"
    return kernel


class _KCluster(ClusteringMixin, BaseEstimator):
    """Base class for k-statistics clustering (reference _kcluster.py:13-86).

    Parameters
    ----------
    metric : callable(x, y) -> distances
        Pairwise-distance kernel on jax arrays.
    n_clusters, init, max_iter, tol, random_state : see reference.
    """

    def __init__(
        self,
        metric: Callable,
        n_clusters: int,
        init: Union[str, DNDarray],
        max_iter: int,
        tol: float,
        random_state: Optional[int],
    ):
        self.n_clusters = n_clusters
        self.init = init
        self.max_iter = max_iter
        self.tol = tol
        self.random_state = random_state

        self._metric = metric
        self._cluster_centers = None
        self._labels = None
        self._inertia = None
        self._n_iter = None

        if random_state is not None:
            ht_random.seed(random_state)

        if isinstance(init, DNDarray):
            if init.shape[0] != n_clusters:
                raise ValueError(
                    f"passed centroids do not match n_clusters: {init.shape[0]} != {n_clusters}"
                )
            self.init = "precomputed"
            self._precomputed = init
        elif init not in ("random", "probability_based", "kmeans++", "k-means++", "batchparallel"):
            raise ValueError(f"Initialization method {init!r} not supported")

    @property
    def cluster_centers_(self) -> DNDarray:
        return self._cluster_centers

    @property
    def labels_(self) -> DNDarray:
        return self._labels

    @property
    def inertia_(self) -> float:
        return self._inertia

    @property
    def n_iter_(self) -> int:
        return self._n_iter

    # ------------------------------------------------------------------
    def _initialize_cluster_centers(self, x: DNDarray) -> jax.Array:
        """Pick initial centroids (reference _kcluster.py:87-195)."""
        k = self.n_clusters
        data = x.larray.astype(jnp.promote_types(x.dtype.jax_type(), jnp.float32))
        n = data.shape[0]
        if self.init == "precomputed":
            return self._precomputed.larray.astype(data.dtype)
        if self.init == "random":
            idx = ht_random.randint(0, n, (k,)).larray
            return data[idx]
        if (
            self.init == "batchparallel"
            and x.split == 0
            and x.comm.size > 1
            and not x.padded
            and n // x.comm.size >= k
        ):
            return self._batchparallel_init(x, data, k)
        # kmeans++ / probability_based (reference _kcluster.py:142-187)
        idx0 = int(ht_random.randint(0, n, (1,)).larray[0])
        centers = data[idx0][None, :]
        for _ in range(1, k):
            d = self._metric(data, centers)
            closest = jnp.min(d, axis=1)
            prob = closest / jnp.sum(closest)
            r = float(ht_random.rand(1).larray[0])
            cum = jnp.cumsum(prob)
            nxt = int(jnp.searchsorted(cum, r))
            nxt = min(nxt, n - 1)
            centers = jnp.concatenate([centers, data[nxt][None, :]], axis=0)
        return centers

    def _batchparallel_init(self, x: DNDarray, data: jax.Array, k: int) -> jax.Array:
        """Scalable batch-parallel init: every device runs a fixed-shape
        kmeans++ over its OWN block (zero communication), the p*k candidate
        centroids are gathered once, and one more kmeans++ over the
        candidates picks the k finals — one (p*k, f) all-gather is the entire
        communication budget, vs the per-step sampling sync of plain
        kmeans++. The whole init is one XLA program."""
        comm = x.comm
        seed = int(ht_random.randint(0, 2**31 - 1, (1,)).larray[0])
        base_key = jax.random.PRNGKey(seed)
        # the PRNG key rides as an OPERAND: a per-call closure over it would
        # bake the key into the traced program as a constant and retrace
        # every init (the H004 bug class) — the cached kernel is keyed on
        # (axis, k, metric) only and every seed hits the same program
        kernel = _batchparallel_kernel(comm.axis_name, k, self._metric)
        return comm.apply(kernel, data, base_key, in_splits=[0, None], out_splits=None)

    def _assign_to_cluster(self, x: DNDarray):
        """Cluster id per sample (reference _kcluster.py:196-209)."""
        data = x.larray.astype(jnp.promote_types(x.dtype.jax_type(), jnp.float32))
        d = self._metric(data, self._cluster_centers.larray)
        labels = jnp.argmin(d, axis=1)
        return self._wrap_labels(labels, x)

    def _wrap_labels(self, labels: jax.Array, x: DNDarray) -> DNDarray:
        # labels are 1-D over SAMPLES: they inherit x's split only when x is
        # sample-split (split=0); a feature-split input (split=1) has every
        # device owning all samples, so its labels are replicated (the
        # reference's split-semantics for 1-D results of a split=1 operand)
        split = 0 if x.split == 0 else None
        labels = labels.astype(types.index_dtype())
        labels = _ensure_split(labels, split, x.comm)
        return DNDarray(
            labels, tuple(labels.shape), types.canonical_heat_type(labels.dtype), split, x.device, x.comm
        )

    def predict(self, x: DNDarray) -> DNDarray:
        """Nearest-centroid labels for new data (reference _kcluster.py:210-254)."""
        if self._cluster_centers is None:
            raise RuntimeError("fit needs to be called before predict")
        if not isinstance(x, DNDarray):
            raise ValueError(f"input needs to be a DNDarray, but was {type(x)}")
        return self._assign_to_cluster(x)

"""K-Medoids clustering (reference: heat/cluster/kmedoids.py).

As in the reference, the update computes the cluster median and then snaps it
to the nearest actual data point (reference kmedoids.py:73-105), so centroids
are always members of the dataset.
"""

from __future__ import annotations

from functools import partial
from typing import Optional, Union

import jax
import jax.numpy as jnp

from ..core import types
from ..core.dndarray import DNDarray, _ensure_split
from ._kcluster import _KCluster
from .kmeans import _sq_dist

__all__ = ["KMedoids"]


@partial(jax.jit, static_argnames=("k",))
def _medoid_step(data: jax.Array, centers: jax.Array, k: int):
    d2 = _sq_dist(data, centers)
    labels = jnp.argmin(d2, axis=1)

    def cluster_medoid(c):
        mask = labels == c
        vals = jnp.where(mask[:, None], data, jnp.nan)
        med = jnp.nanmedian(vals, axis=0)
        # snap to the nearest member of the cluster
        dist_to_med = jnp.sum((data - med[None, :]) ** 2, axis=1)
        dist_to_med = jnp.where(mask, dist_to_med, jnp.inf)
        idx = jnp.argmin(dist_to_med)
        return jnp.where(jnp.any(mask), data[idx], centers[c])

    new_centers = jax.vmap(cluster_medoid)(jnp.arange(k))
    inertia = jnp.sum(jnp.sqrt(jnp.take_along_axis(d2, labels[:, None], axis=1)))
    shift = jnp.sum((new_centers - centers) ** 2)
    return new_centers, labels, inertia, shift


@partial(jax.jit, static_argnames=("k", "n_steps"))
def _medoid_run(data: jax.Array, centers: jax.Array, k: int, n_steps: int):
    """``n_steps`` fused iterations in ONE XLA program (the kmeans
    ``_lloyd_run`` pattern: one dispatch per chunk instead of per step)."""

    def body(i, carry):
        centers, _, _, _ = carry
        return _medoid_step.__wrapped__(data, centers, k)

    # the first step seeds the carry with the exact output types
    first = _medoid_step.__wrapped__(data, centers, k)
    return jax.lax.fori_loop(1, n_steps, body, first)


class KMedoids(_KCluster):
    """K-Medoids clustering (reference kmedoids.py:14-139)."""

    def __init__(
        self,
        n_clusters: int = 8,
        init: Union[str, DNDarray] = "random",
        max_iter: int = 300,
        random_state: Optional[int] = None,
    ):
        if isinstance(init, str) and init in ("kmeans++", "k-means++"):
            init = "probability_based"
        super().__init__(
            metric=_sq_dist,  # module-level identity: kernels cache across instances
            n_clusters=n_clusters,
            init=init,
            max_iter=max_iter,
            tol=0.0,
            random_state=random_state,
        )

    def fit(self, x: DNDarray) -> "KMedoids":
        """Cluster ``x`` (reference kmedoids.py:106-143)."""
        if not isinstance(x, DNDarray):
            raise ValueError(f"input needs to be a DNDarray, but was {type(x)}")
        if x.ndim != 2:
            raise ValueError(f"input needs to be 2D, but was {x.ndim}D")
        data = x.larray.astype(jnp.promote_types(x.dtype.jax_type(), jnp.float32))
        centers = self._initialize_cluster_centers(x)

        labels = inertia = None
        done = 0
        while done < self.max_iter:
            # fused chunks of up to 8 iterations per dispatch; convergence
            # checked at chunk boundaries (the kmeans pattern). Medoids snap
            # to data points, so exact-zero shift is the fixed point.
            chunk = min(8, self.max_iter - done)
            centers, labels, inertia, shift = _medoid_run(data, centers, self.n_clusters, chunk)
            done += chunk
            if float(shift) == 0.0:
                break

        self._n_iter = done
        self._inertia = float(inertia) if inertia is not None else None
        self._cluster_centers = DNDarray(
            _ensure_split(centers, None, x.comm),
            tuple(centers.shape),
            types.canonical_heat_type(centers.dtype),
            None,
            x.device,
            x.comm,
        )
        self._labels = self._wrap_labels(labels, x)
        return self

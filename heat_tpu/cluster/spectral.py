"""Spectral clustering (reference: heat/cluster/spectral.py).

Pipeline identical to the reference (spectral.py:103-189): RBF/eNeighbour
affinity → normalized symmetric Laplacian → Lanczos eigen-embedding → KMeans
in the embedding space. The Lanczos dots ride sharded reductions; the small
(m×m) tridiagonal eigenproblem is solved replicated, as in the reference.
"""

from __future__ import annotations

from typing import Optional, Union

import jax.numpy as jnp

from ..core import types
from ..core.base import BaseEstimator, ClusteringMixin
from ..core.dndarray import DNDarray, _ensure_split
from ..core.linalg import solver
from ..graph import Laplacian
from ..spatial import distance
from .kmeans import KMeans

__all__ = ["Spectral"]


class Spectral(ClusteringMixin, BaseEstimator):
    """Spectral clustering on the graph Laplacian's eigen-embedding
    (reference spectral.py:14-102 for the constructor contract)."""

    def __init__(
        self,
        n_clusters: Optional[int] = None,
        gamma: float = 1.0,
        metric: str = "rbf",
        laplacian: str = "fully_connected",
        threshold: float = 1.0,
        boundary: str = "upper",
        n_lanczos: int = 300,
        assign_labels: str = "kmeans",
        **params,
    ):
        self.n_clusters = n_clusters
        self.gamma = gamma
        self.metric = metric
        self.laplacian = laplacian
        self.threshold = threshold
        self.boundary = boundary
        self.n_lanczos = n_lanczos
        self.assign_labels = assign_labels

        if metric == "rbf":
            sigma = jnp.sqrt(1.0 / (2.0 * gamma))
            sim = lambda x: distance.rbf(x, sigma=float(sigma), quadratic_expansion=True)
        elif metric == "euclidean":
            sim = lambda x: distance.cdist(x, quadratic_expansion=True)
        else:
            raise NotImplementedError(f"Metric {metric} is currently not implemented")
        if laplacian == "fully_connected":
            self._laplacian = Laplacian(sim, definition="norm_sym", mode="fully_connected")
        elif laplacian == "eNeighbour":
            self._laplacian = Laplacian(
                sim,
                definition="norm_sym",
                mode="eNeighbour",
                threshold_key=boundary,
                threshold_value=threshold,
            )
        else:
            raise NotImplementedError(f"Laplacian {laplacian} is currently not implemented")
        if assign_labels != "kmeans":
            raise NotImplementedError(
                f"Assignment-method {assign_labels} is currently not implemented"
            )
        self._cluster = KMeans(
            n_clusters=n_clusters if n_clusters is not None else 8, **params
        )
        self._labels = None

    @property
    def labels_(self) -> DNDarray:
        return self._labels

    def _spectral_embedding(self, x: DNDarray):
        """Lanczos eigen-embedding of the Laplacian (reference spectral.py:103-140)."""
        L = self._laplacian.construct(x)
        m = min(self.n_lanczos, L.shape[0])
        V, T = solver.lanczos(L, m)
        # eigendecomposition of the small tridiagonal T (replicated)
        evals, evecs = jnp.linalg.eigh(T.larray)
        # ascending order; embedding = V @ evecs
        emb = V.larray @ evecs
        emb = _ensure_split(emb, x.split, x.comm)
        embedding = DNDarray(
            emb, tuple(emb.shape), types.canonical_heat_type(emb.dtype), x.split, x.device, x.comm
        )
        return evals, embedding

    def fit(self, x: DNDarray) -> "Spectral":
        """Embed and cluster (reference spectral.py:141-170)."""
        if not isinstance(x, DNDarray):
            raise ValueError(f"input needs to be a DNDarray, but was {type(x)}")
        if x.split is not None and x.split != 0:
            raise NotImplementedError("Not implemented for other splitting-axes")
        eigenvalues, eigenvectors = self._spectral_embedding(x)
        if self.n_clusters is None:
            # eigengap heuristic (reference spectral.py:152-157)
            import numpy as np

            ev = np.asarray(eigenvalues)
            diff = np.diff(ev)
            self.n_clusters = int(np.argmax(diff) + 1)
            self._cluster.n_clusters = self.n_clusters
        components = eigenvectors[:, : self.n_clusters]
        self._cluster.fit(components.balance_())
        self._labels = self._cluster.labels_
        return self

    def predict(self, x: DNDarray) -> DNDarray:
        """Labels via embedding + trained KMeans (reference spectral.py:171-189)."""
        if not isinstance(x, DNDarray):
            raise ValueError(f"input needs to be a DNDarray, but was {type(x)}")
        _, eigenvectors = self._spectral_embedding(x)
        components = eigenvectors[:, : self.n_clusters]
        return self._cluster.predict(components)

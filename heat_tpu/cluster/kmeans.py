"""K-Means clustering (reference: heat/cluster/kmeans.py).

The reference's Lloyd loop (kmeans.py:102-139) computes cdist against
replicated centroids, argmin-assigns, then per-cluster masked mean updates —
k Allreduces of (1, f) rows per iteration (kmeans.py:73-100). Here the whole
iteration is ONE jitted XLA program: the assignment is a quadratic-expansion
matmul (MXU), the update is a one-hot matmul (``onehotᵀ @ x`` — MXU again),
and the only collective is the psum GSPMD inserts for the row-sharded sums.
"""

from __future__ import annotations

from functools import partial
from typing import Optional, Union

import jax
import jax.numpy as jnp

from ..core import types
from ..core.dndarray import DNDarray, _ensure_split
from ..spatial.distance import _sq_euclidian_fast as _sq_dist
from ._kcluster import _KCluster

__all__ = ["KMeans"]


@partial(jax.jit, static_argnames=("k", "n_steps"))
def _lloyd_run(data: jax.Array, centers: jax.Array, k: int, n_steps: int):
    """``n_steps`` fused Lloyd iterations in ONE XLA program — amortizes the
    per-dispatch latency (the reference pays an MPI round per iteration; a
    remote-dispatch TPU pays one RPC per *program*, so fusing the loop is the
    TPU-side analog of batching the collectives).

    The |x|² term of the quadratic-expansion distance is loop-invariant: the
    argmin over centers only sees −2x·cᵀ + |c|², and the inertia needs just
    the scalar Σ|x|². Hoisting it saves an (n, f) square+reduce — pure HBM
    bandwidth — per iteration."""
    xsq_sum = jnp.sum(data * data)

    def body(i, carry):
        centers, _, _, _ = carry
        return _lloyd_iter(data, centers, k, xsq_sum)

    acc = jnp.zeros((), data.dtype)
    out = jax.lax.fori_loop(
        0, n_steps, body, (centers, jnp.zeros(data.shape[0], jnp.int32), acc, acc)
    )
    return out


def _lloyd_iter(data: jax.Array, centers: jax.Array, k: int, xsq_sum=None):
    if xsq_sum is None:
        xsq_sum = jnp.sum(data * data)
    # score = d² − |x|² (row-constant offset): same argmin, cheaper to form
    score = jnp.sum(centers * centers, axis=1) - 2.0 * (data @ centers.T)  # (n, k)
    labels = jnp.argmin(score, axis=1).astype(jnp.int32)
    onehot = jax.nn.one_hot(labels, k, dtype=data.dtype)  # (n, k)
    counts = jnp.sum(onehot, axis=0)  # (k,)
    sums = onehot.T @ data  # (k, f) — MXU; psum over the sharded rows
    new_centers = jnp.where(
        counts[:, None] > 0, sums / jnp.maximum(counts[:, None], 1.0), centers
    )
    # labels are the argmin, so the assigned distance is the row minimum —
    # a fused reduction instead of a gather (take_along_axis is ~100x slower
    # than the min on TPU for this shape); adding Σ|x|² restores true d²
    inertia = jnp.maximum(jnp.sum(jnp.min(score, axis=1)) + xsq_sum, 0.0)
    shift = jnp.sum((new_centers - centers) ** 2)
    return new_centers, labels, inertia, shift


_lloyd_step = partial(jax.jit, static_argnames=("k",))(_lloyd_iter)
"""One Lloyd iteration (data (n, f) row-sharded, centers (k, f) replicated)."""


class KMeans(_KCluster):
    """K-Means with Lloyd's algorithm (reference kmeans.py:14-139).

    Parameters mirror the reference: n_clusters=8, init='random',
    max_iter=300, tol=1e-4, random_state=None. ``use_fused`` (beyond the
    reference) selects the single-pass samples-in-lanes pallas Lloyd kernel
    (ops/lloyd.py): ``None`` auto-selects it on TPU backends, where it reads
    the operand once per iteration — measured 1.65x the jnp path at ~90% of
    the v5e HBM roofline (benchmarks/TPU_WINDOW_r04.json);
    ``True`` forces it (interpret mode off-TPU — the testing path), ``False``
    pins the jnp oracle path.
    """

    def __init__(
        self,
        n_clusters: int = 8,
        init: Union[str, DNDarray] = "random",
        max_iter: int = 300,
        tol: float = 1e-4,
        random_state: Optional[int] = None,
        use_fused: Optional[bool] = None,
    ):
        if isinstance(init, str) and init in ("kmeans++", "k-means++"):
            init = "probability_based"
        self.use_fused = use_fused
        super().__init__(
            metric=_sq_dist,  # module-level identity: kernels cache across instances
            n_clusters=n_clusters,
            init=init,
            max_iter=max_iter,
            tol=tol,
            random_state=random_state,
        )

    def _fused_mode(self, x: DNDarray):
        """Resolve the Lloyd dispatch: ('single'|'sharded', interpret) or
        (None, False) for the jnp path."""
        from ..ops import lloyd as _lloyd

        n, f = int(x.shape[0]), int(x.shape[1])
        k = self.n_clusters
        if self.use_fused is False:
            return None, False
        if _lloyd.fused_supported(n, f, k):
            return "single", False
        if x.split == 0 and _lloyd.fused_sharded_supported(f, k):
            return "sharded", False
        if not self.use_fused:
            return None, False  # auto never interprets: jnp is faster off-TPU
        # forced off-TPU (the testing path): pallas interpret mode
        if x.split == 0 and f <= 512 and k <= 128:
            return "sharded", True
        if len(jax.devices()) == 1 and f <= 512 and k <= 128:
            return "single", True
        # use_fused=True could not be honored — say so loudly instead of
        # letting a test of the fused path pass vacuously on the jnp oracle
        import warnings

        warnings.warn(
            f"KMeans(use_fused=True) falling back to the jnp path: shape "
            f"(n={n}, f={f}, k={k}, split={x.split}) has no fused dispatch "
            "(needs f<=512, k<=128, and split=0 or a single device)",
            stacklevel=3,
        )
        return None, False

    def fit(self, x: DNDarray) -> "KMeans":
        """Cluster ``x`` (n_samples, n_features) (reference kmeans.py:102-139)."""
        from ..ops import lloyd as _lloyd

        if not isinstance(x, DNDarray):
            raise ValueError(f"input needs to be a DNDarray, but was {type(x)}")
        if x.ndim != 2:
            raise ValueError(f"input needs to be 2D, but was {x.ndim}D")
        centers = self._initialize_cluster_centers(x)
        mode, interpret = self._fused_mode(x)
        fdtype = jnp.promote_types(x.dtype.jax_type(), jnp.float32)
        # bfloat16 stays bfloat16 through the fused kernel (half the HBM
        # traffic of the f32 stream; accumulators are f32 inside) — the jnp
        # path and the centroids always compute in at-least-f32
        keep_bf16 = mode is not None and x.dtype.jax_type() == jnp.bfloat16
        ddtype = x.dtype.jax_type() if keep_bf16 else fdtype
        if mode == "sharded":
            # the kernel masks each device's share of the global pad itself,
            # so it consumes the PHYSICAL payload
            data = x.parray.astype(ddtype)
        else:
            data = x.larray.astype(ddtype)
        centers = jnp.asarray(centers, fdtype)

        # iterations run in fused chunks of up to 8 per dispatch; convergence
        # is checked at chunk boundaries (coarser than the reference's
        # per-iteration check, identical fixed point). The loop-invariant
        # operands (the samples-in-lanes transpose and Σ|x|²) are computed
        # ONCE here, not per chunk — they are full-data passes.
        labels = None
        inertia = None
        done = 0
        n_global = int(x.shape[0])
        xT = xsq = None
        while done < self.max_iter:
            chunk = min(8, self.max_iter - done)
            try:
                if mode == "single":
                    if xT is None:
                        xT, xsq = _lloyd._prepare_run_operands(data, self.n_clusters)
                    centers, labels, inertia, shift = _lloyd.fused_lloyd_run(
                        data, centers, self.n_clusters, chunk, interpret=interpret,
                        xT=xT, xsq_sum=xsq,
                    )
                elif mode == "sharded":
                    if xsq is None:
                        xsq = _lloyd._sharded_xsq(data, n_global=n_global)
                    centers, labels, inertia, shift = _lloyd.fused_lloyd_run_sharded(
                        data, centers, self.n_clusters, x.comm, n_global, chunk,
                        interpret=interpret, xsq_sum=xsq,
                    )
                else:
                    centers, labels, inertia, shift = _lloyd_run(
                        data, centers, self.n_clusters, chunk
                    )
                # the host read is INSIDE the try: on async backends a kernel
                # that lowered fine can still fail at execution, surfacing
                # only at this scalar fetch
                shift_val = float(shift)
            except Exception as exc:
                if mode is None:
                    raise
                # the pallas kernel failed to lower/run on this backend
                # (Mosaic support varies): fall back to the jnp oracle path
                # rather than failing the fit — loudly, never silently
                import warnings

                warnings.warn(
                    "KMeans fused Lloyd kernel failed on this backend "
                    f"({repr(exc)[:160]}); falling back to the jnp path",
                    stacklevel=2,
                )
                mode = None
                data = x.larray.astype(fdtype)
                continue
            done += chunk
            if shift_val <= self.tol:
                break

        self._n_iter = done
        self._inertia = float(inertia) if inertia is not None else None
        self._cluster_centers = DNDarray(
            _ensure_split(centers, None, x.comm),
            tuple(centers.shape),
            types.canonical_heat_type(centers.dtype),
            None,
            x.device,
            x.comm,
        )
        self._labels = self._wrap_labels(labels, x)
        return self

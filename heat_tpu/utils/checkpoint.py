"""Checkpoint / resume subsystem.

The reference checkpoints *data* only (``ht.save``/``ht.load`` to
HDF5/NetCDF/CSV, reference io.py:149-227); it has **no** model/optimizer
checkpointing — DASO's ``DetectMetricPlateau`` exposes get_state/set_state
dicts that nothing serializes (reference optim/utils.py:72-108, SURVEY.md §5).
This module closes that gap for the TPU build:

* :func:`save_checkpoint` / :func:`load_checkpoint` — any pytree of arrays to
  a single msgpack file (flax.serialization), atomically (write tmp + rename),
  with a retention policy (``keep``) and step-tagged filenames.
* :func:`latest_step` — discover the newest step in a directory.
* Trainer integration: ``DataParallel.state_dict/load_state_dict`` and
  ``DASO.state_dict/load_state_dict`` (params, optimizer state, schedule
  counters, plateau-controller state) round-trip through these files, so a
  killed training run resumes exactly — the failure-recovery story MPI
  fail-stop never had.

Arrays come back as numpy; feed them to ``jax.device_put`` / the trainer's
``load_state_dict`` which re-establishes shardings (single-controller JAX
re-shards on first use, so a checkpoint written on one mesh shape restores
onto another — elasticity the reference cannot express).
"""

from __future__ import annotations

import os
import re
from typing import Any, Dict, Optional

import jax
import numpy as np
from flax import serialization

__all__ = [
    "latest_step",
    "load_checkpoint",
    "save_checkpoint",
]

_FILE_RE = re.compile(r"^ckpt_(\d+)\.msgpack$")


def _to_host(tree: Any) -> Any:
    """Device arrays -> numpy (gathers sharded jax.Arrays to host).

    Arrays spanning non-addressable devices (multi-host meshes) cannot be
    read with ``np.asarray``; those are allgathered across processes first.
    """

    from ..core.dndarray import DNDarray

    def to_np(x):
        if isinstance(x, DNDarray):
            # a DNDarray serializes as its LOGICAL global array (not the
            # padded physical payload its pytree leaf carries); falling
            # through to the jax.Array handling keeps the multi-host
            # allgather path below
            x = x.larray
        if not (hasattr(x, "dtype") or hasattr(x, "__array__")):
            return x
        if isinstance(x, jax.Array) and not x.is_fully_addressable:
            from jax.experimental import multihost_utils

            x = multihost_utils.process_allgather(x, tiled=True)
        return np.asarray(x)

    return jax.tree.map(to_np, tree, is_leaf=lambda x: isinstance(x, DNDarray))


def save_checkpoint(directory: str, tree: Any, step: int = 0, keep: int = 3) -> str:
    """Serialize ``tree`` to ``directory/ckpt_{step}.msgpack`` atomically.

    Older step files beyond the newest ``keep`` are deleted (``keep <= 0``
    keeps everything). Returns the written path.
    """
    os.makedirs(directory, exist_ok=True)
    payload = serialization.to_bytes(_to_host(tree))
    path = os.path.join(directory, f"ckpt_{int(step)}.msgpack")
    tmp = path + ".tmp"
    with open(tmp, "wb") as f:
        f.write(payload)
    os.replace(tmp, path)  # atomic on POSIX: no torn checkpoints on crash
    if keep > 0:
        steps = _all_steps(directory)
        for old in steps[:-keep]:
            if old == int(step):
                # never cull the checkpoint just written (e.g. a resumed run
                # whose step counter restarted below existing step tags)
                continue
            try:
                os.remove(os.path.join(directory, f"ckpt_{old}.msgpack"))
            except OSError:  # pragma: no cover - concurrent cleanup
                pass
    return path


def _all_steps(directory: str):
    steps = []
    try:
        for name in os.listdir(directory):
            m = _FILE_RE.match(name)
            if m:
                steps.append(int(m.group(1)))
    except FileNotFoundError:
        pass
    return sorted(steps)


def latest_step(directory: str) -> Optional[int]:
    """Newest checkpointed step in ``directory``, or None."""
    steps = _all_steps(directory)
    return steps[-1] if steps else None


def load_checkpoint(directory: str, target: Any, step: Optional[int] = None) -> Any:
    """Restore a checkpoint into the structure of ``target``.

    ``target`` is a template pytree (e.g. a freshly-initialized state dict);
    its leaves' shapes/dtypes validate the restore. ``step=None`` loads the
    newest. Accepts a direct file path in ``directory`` too.
    """
    if os.path.isfile(directory):
        path = directory
    else:
        if step is None:
            step = latest_step(directory)
            if step is None:
                raise FileNotFoundError(f"no checkpoints in {directory!r}")
        path = os.path.join(directory, f"ckpt_{int(step)}.msgpack")
    with open(path, "rb") as f:
        return serialization.from_bytes(target, f.read())

"""Verified, sharded, crash-consistent checkpoint / resume subsystem.

The reference checkpoints *data* only (``ht.save``/``ht.load`` to
HDF5/NetCDF/CSV, reference io.py:149-227); it has **no** model/optimizer
checkpointing — DASO's ``DetectMetricPlateau`` exposes get_state/set_state
dicts that nothing serializes (reference optim/utils.py:72-108, SURVEY.md §5)
and MPI fail-stop means a killed run is a dead run. This module is the TPU
build's answer, surfaced as ``ht.checkpoint``:

Manifest format
---------------
A checkpoint at ``step`` is a JSON **manifest** ``ckpt_<step>.manifest.json``
plus a **payload directory** of per-leaf files the manifest references:

* DNDarray leaves are written as **per-host shard files** — one file per
  mesh rank with a non-empty logical block (``DNDarray.ranked_shards``, the
  same shard/trim protocol the streaming ``save_*`` writers use), so no host
  allocation ever equals the global array and no allgather is paid. The
  manifest records global shape/dtype/split, the mesh size at save time, and
  each shard file's row range along the split axis.
* Other array leaves (``jax.Array``/numpy) are written whole as one payload
  file each (``.npy`` for native dtypes; a raw buffer + recorded dtype name
  for ml_dtypes extensions like bfloat16, which npy round-trips as void).
* Plain Python leaves (ints, floats incl. inf/nan, bools, strings, None)
  are inlined in the manifest.

Every payload file's SHA-256 is recorded in the manifest.

Commit point & crash consistency
--------------------------------
Payload files are staged first (each atomically under its own name by the
one process that writes it); the **manifest rename is the single commit
point**, routed through ``resilience.atomic_write`` so only the owning
process (``multihost.io_owner()``) publishes it. A crash at any instant
leaves either the previous checkpoint or the new one — never a hybrid: an
uncommitted payload directory is invisible to restore and swept as debris by
a later save's GC. Overwriting an existing step stages into an alternate
payload directory (``ckpt_<step>.r1``) so the committed payload is never
mutated before the new manifest lands.

Verified + elastic restore
--------------------------
``load_checkpoint`` verifies the manifest and every payload checksum before
reconstructing anything. A torn/corrupt/incomplete newest checkpoint emits a
:class:`CheckpointCorruptWarning`, records ``telemetry`` checkpoint events,
and **falls back to the newest checkpoint that verifies**; ``strict=True``
(or an explicit ``step=``) opts out and raises :class:`CheckpointCorruptError`
naming the path, step, and the fallback decision taken. DNDarray leaves
restore **elastically**: a checkpoint saved on a p-device mesh reloads onto
any current mesh by reading each new device's block from the overlapping
saved shard files (``io._sharded_ingest`` — per-range reads, no global host
copy), bitwise identical to the saved global array.

Deliberate trade-off: a manifest restore reads payload files twice — one
full checksum pass to SELECT the step (fallback must decide before any
reconstruction), then the reconstruction's reads. The passes cannot merge:
elastic restore reads only this host's ranges, while verification must cover
whole files. Legacy blobs, whose verify decode IS the restore decode, are
memoized instead (one read+decode total on the load path); saves hash from
the write stream, never a readback.

Retention & GC
--------------
Keep-N GC is validity-aware: it never deletes the last checkpoint that
verifies (an unverifiable newest cannot cause a valid older checkpoint to be
culled), and it sweeps orphaned temp/shard debris — legacy
``ckpt_*.msgpack.tmp`` files, ``*.tmp-*`` staging files and payload
directories no committed manifest references — once they are older than the
newest committed manifest. GC failures degrade to a warning (the save still
succeeds); the debris waits for the next sweep.

Legacy single-blob ``ckpt_<step>.msgpack`` files (flax.serialization) remain
loadable behind the same error surface: a truncated/corrupt blob raises
:class:`CheckpointCorruptError` (or falls back) instead of a cryptic flax
deserialization error.

Fault sites (``core/resilience.py``): ``checkpoint.write`` (payload-file
attempts), ``checkpoint.commit`` (manifest publication), ``checkpoint.restore``
(verify/restore reads) — all retried for transient ``OSError``s — and
``checkpoint.gc`` (each deletion; degrades). All four are in the ``ci``
ambient preset.

Arrays come back as numpy (DNDarray leaves as DNDarrays on the current
mesh); feed them to the trainer's ``load_state_dict``, which re-establishes
shardings.
"""

from __future__ import annotations

import hashlib
import json
import os
import re
import shutil
import warnings
from typing import Any, Dict, List, Optional, Tuple

import jax
import numpy as np

from ..core import memledger, resilience, telemetry

__all__ = [
    "CheckpointCorruptError",
    "CheckpointCorruptWarning",
    "MANIFEST_VERSION",
    "all_steps",
    "gc_checkpoints",
    "latest_step",
    "load_checkpoint",
    "save_checkpoint",
    "verify_checkpoint",
]

MANIFEST_VERSION = 1
_FORMAT_NAME = "heat-tpu-checkpoint"

_MANIFEST_RE = re.compile(r"^ckpt_(\d+)\.manifest\.json$")
_LEGACY_RE = re.compile(r"^ckpt_(\d+)\.msgpack$")
_LEGACY_TMP_RE = re.compile(r"^ckpt_(\d+)\.msgpack\.tmp$")
_PAYLOAD_RE = re.compile(r"^ckpt_(\d+)(\.r\d+)?$")

# restore-time forcing attribution: checkpoint writes are I/O
_T_IO = telemetry.force_trigger("io")


def _phase(phase: str, step=None, **fields) -> None:
    """One ``checkpoint_phase`` trace-timeline event (verbose mode only) —
    phase boundaries the exported trace shows WITHOUT disturbing the
    ``telemetry.checkpoint_events()`` lifecycle counts the suites pin."""
    if telemetry._MODE >= 2:
        telemetry.record_event("checkpoint_phase", phase=phase, step=step, **fields)


class CheckpointCorruptError(RuntimeError):
    """A checkpoint failed verification (torn payload, checksum mismatch,
    truncated legacy msgpack) and the configured policy forbids — or could
    not find — a fallback. The message names the path, the step, and the
    fallback decision taken."""


class CheckpointCorruptWarning(UserWarning):
    """Restore skipped one or more unverifiable checkpoints and fell back to
    the newest one that verifies."""


# ----------------------------------------------------------------------
# small helpers
# ----------------------------------------------------------------------
def _proc() -> int:
    from ..core import multihost

    return multihost.process_index()


# the same cleanup primitive atomic_write uses — one definition to drift
_unlink_quiet = resilience._unlink_quiet


def _np_dtype(name: str) -> np.dtype:
    """Resolve a recorded dtype name, including ml_dtypes extensions
    (``bfloat16``/``float8_*``) numpy alone cannot name."""
    try:
        return np.dtype(name)
    except TypeError:
        import ml_dtypes

        return np.dtype(getattr(ml_dtypes, name))


def _is_native_npy_dtype(dtype: np.dtype) -> bool:
    """Whether ``.npy`` round-trips this dtype faithfully. ml_dtypes
    extensions (kind 'V' descrs) load back as void — those take the raw
    format with the dtype name recorded in the manifest."""
    return dtype.kind in "biufc" and dtype.names is None


def _check_serializable_dtype(dtype: np.dtype, where: str) -> None:
    """Refuse at SAVE time any dtype restore could not round-trip: the raw
    fallback can write unicode/object/datetime buffers that checksum cleanly
    but are unrestorable (``_np_dtype`` cannot resolve the name; object
    arrays would serialize raw pointers) — a 'verified' checkpoint that is
    silent data loss. Mirrors ``_encode_py``'s reject-unknown stance."""
    if _is_native_npy_dtype(dtype):
        return
    try:
        # the raw format is ONLY for ml_dtypes extensions; np.dtype(name)
        # would happily "resolve" object/unicode/datetime names too
        import ml_dtypes

        ok = np.dtype(getattr(ml_dtypes, dtype.name)) == dtype
    except Exception:  # noqa: BLE001 - unresolvable name = not serializable
        ok = False
    if not ok:
        raise TypeError(
            f"checkpoint leaf {where!r} has dtype {dtype!r}, which no restore "
            "could round-trip (supported: bool/int/uint/float/complex and "
            "ml_dtypes extensions like bfloat16)"
        )


def _sha256_file(path: str, site: str = "checkpoint.restore") -> str:
    """Streaming SHA-256 of ``path`` (1 MiB chunks — never the whole file in
    memory); the read is retried like any other block read."""

    def _hash() -> str:
        h = hashlib.sha256()
        with open(path, "rb") as fh:
            while True:
                chunk = fh.read(1 << 20)
                if not chunk:
                    break
                h.update(chunk)
        return h.hexdigest()

    return resilience.call_with_retries(site, _hash)


def _to_host_array(x) -> np.ndarray:
    """Device array -> host numpy; arrays spanning non-addressable devices
    (multi-host meshes) are allgathered across processes first."""
    if isinstance(x, jax.Array) and not x.is_fully_addressable:
        from jax.experimental import multihost_utils  # pragma: no cover - multi-host

        x = multihost_utils.process_allgather(x, tiled=True)  # pragma: no cover
    return np.asarray(x)


def _is_arraylike(x) -> bool:
    return hasattr(x, "dtype") or hasattr(x, "__array__")


def _encode_py(v):
    """JSON-safe encoding of a plain Python leaf (nan/inf floats included —
    the plateau controller's ``best``/``mode_worse`` start at inf)."""
    if isinstance(v, float):
        if np.isfinite(v):
            return v
        return {"__nonfinite__": repr(v)}
    if v is None or isinstance(v, (bool, int, str)):
        return v
    raise TypeError(
        f"checkpoint leaf of type {type(v).__name__} is not serializable "
        "(arrays, DNDarrays, and plain Python scalars/strings are)"
    )


def _decode_py(v):
    if isinstance(v, dict) and "__nonfinite__" in v:
        return float(v["__nonfinite__"])
    return v


def _flatten_with_paths(tree) -> Tuple[List[str], List[Any], Any]:
    """Flatten ``tree`` with DNDarrays as leaves; path strings key the
    manifest entries so save/restore match by structure, not by position."""
    from ..core.dndarray import DNDarray

    leaves_with_paths, treedef = jax.tree_util.tree_flatten_with_path(
        tree, is_leaf=lambda x: isinstance(x, DNDarray)
    )
    paths = [jax.tree_util.keystr(p) for p, _ in leaves_with_paths]
    return paths, [leaf for _, leaf in leaves_with_paths], treedef


# ----------------------------------------------------------------------
# directory enumeration
# ----------------------------------------------------------------------
def _committed(directory: str) -> Dict[int, str]:
    """step -> committed artifact name (manifest preferred over a legacy
    blob carrying the same step tag)."""
    out: Dict[int, str] = {}
    try:
        names = os.listdir(directory)
    except FileNotFoundError:
        return out
    for name in names:
        m = _LEGACY_RE.match(name)
        if m:
            out.setdefault(int(m.group(1)), name)
    for name in names:
        m = _MANIFEST_RE.match(name)
        if m:
            out[int(m.group(1))] = name  # manifest wins
    return out


def _all_steps(directory: str) -> List[int]:
    return sorted(_committed(directory))


def all_steps(directory: str) -> List[int]:
    """Every committed checkpoint step in ``directory`` (manifest-based and
    legacy msgpack), sorted ascending. Commitment, not validity: a step may
    still fail :func:`verify_checkpoint`."""
    return _all_steps(directory)


def latest_step(directory: str) -> Optional[int]:
    """Newest committed step in ``directory``, or None."""
    steps = _all_steps(directory)
    return steps[-1] if steps else None


def _manifest_path(directory: str, step: int) -> str:
    return os.path.join(directory, f"ckpt_{int(step)}.manifest.json")


def _legacy_path(directory: str, step: int) -> str:
    return os.path.join(directory, f"ckpt_{int(step)}.msgpack")


def _read_manifest(directory: str, step: int) -> dict:
    path = _manifest_path(directory, step)

    def _read():
        with open(path, "r") as fh:
            return json.load(fh)

    return resilience.call_with_retries("checkpoint.restore", _read)


# ----------------------------------------------------------------------
# payload writers
# ----------------------------------------------------------------------
class _HashingWriter:
    """File-like pass-through that SHA-256-hashes every byte it writes, so
    the writer's checksum comes from the write stream itself — no readback
    of a file whose bytes are still in memory. (No ``fileno``: numpy then
    takes its buffered ``write()`` path instead of bypassing via tofile.)"""

    __slots__ = ("fh", "h", "n")

    def __init__(self, fh):
        self.fh = fh
        self.h = hashlib.sha256()
        self.n = 0

    def write(self, b) -> int:
        self.h.update(b)
        self.n += len(b)
        return self.fh.write(b)


def _write_payload_file(path: str, arr: np.ndarray) -> Tuple[str, int]:
    """Write one payload file atomically under ITS OWN name: private temp,
    then a rename by the (single) process writing it. Not
    ``resilience.atomic_write`` — that gates the rename on ``io_owner()``,
    which is correct for a path every controller writes cooperatively but
    wrong here, where each shard file has exactly one writer. Transient
    ``OSError``s re-run the whole attempt (``checkpoint.write`` site).
    Returns ``(sha256_hex, nbytes)`` of the published file, hashed from the
    write stream."""
    # np.asarray, NOT ascontiguousarray: the latter promotes 0-d scalars to
    # 1-d, corrupting the recorded shape; np.save/tobytes copy as needed
    arr = np.asarray(arr)
    native = _is_native_npy_dtype(arr.dtype)

    def _attempt() -> Tuple[str, int]:
        tmp = f"{path}.tmp-{os.getpid()}-{_proc()}"
        try:
            with open(tmp, "wb") as fh:
                w = _HashingWriter(fh)
                if native:
                    np.save(w, arr)
                else:
                    w.write(arr.tobytes())
            os.replace(tmp, path)
            return w.h.hexdigest(), w.n
        except BaseException:
            _unlink_quiet(tmp)
            raise

    return resilience.call_with_retries("checkpoint.write", _attempt)


def _file_entry(payload_rel: str, fname: str, dtype: np.dtype, shape: Tuple[int, ...]) -> dict:
    return {
        "file": f"{payload_rel}/{fname}",
        "format": "npy" if _is_native_npy_dtype(dtype) else "raw",
        "dtype": dtype.name,
        "shape": [int(s) for s in shape],
        # filled by the WRITER from its hash-on-write stream; the owner only
        # hashes files other hosts published (after the barrier)
        "sha256": None,
        "bytes": None,
    }


def _save_dndarray(payload_dir: str, payload_rel: str, base: str, leaf, host_arr) -> dict:
    """Write a DNDarray leaf as per-host shard files; return its manifest
    entry. The writer fills each shard's checksum from its own write stream;
    shards published by OTHER hosts stay ``sha256: None`` for the owner to
    hash after the barrier. ``host_arr`` is the pre-materialized host copy
    for the replicated/0-d branch (materialization may be collective and
    happens in the save's phase-1, before any deferred-error file I/O)."""
    split = leaf.split
    dtype = np.dtype(leaf.dtype.jax_type())
    _check_serializable_dtype(dtype, base)
    entry: dict = {
        "kind": "dndarray",
        "gshape": [int(s) for s in leaf.shape],
        "dtype": dtype.name,
        "split": None if split is None else int(split),
        "mesh_size": int(leaf.comm.size),
        "files": [],
    }
    if split is None or leaf.ndim == 0:
        fname = f"{base}.shard_full"
        frag = _file_entry(payload_rel, fname, dtype, leaf.shape)
        frag["rank"] = None
        if _from_owner():  # a replicated value has one writer
            frag["sha256"], frag["bytes"] = _write_payload_file(
                os.path.join(payload_dir, fname), host_arr
            )
        entry["files"].append(frag)
        return entry
    counts, displs = leaf.comm.counts_displs_shape(leaf.shape, split)
    # the file LIST covers every rank with a non-empty logical block (other
    # hosts write theirs); the shapes are deterministic block arithmetic
    frag_by_rank = {}
    for r in range(leaf.comm.size):
        if counts[r]:
            bshape = list(leaf.shape)
            bshape[split] = counts[r]
            frag = _file_entry(payload_rel, f"{base}.shard_{r:05d}", dtype, bshape)
            frag["rank"] = r
            frag["start"] = int(displs[r])
            frag["stop"] = int(displs[r] + counts[r])
            frag_by_rank[r] = frag
            entry["files"].append(frag)
    with _T_IO:
        for rank, block in leaf.ranked_shards():
            frag = frag_by_rank[rank]
            frag["sha256"], frag["bytes"] = _write_payload_file(
                os.path.join(payload_dir, f"{base}.shard_{rank:05d}"), block
            )
    return entry


def _from_owner() -> bool:
    from ..core import multihost

    return multihost.io_owner()


def _payload_rel_for_save(directory: str, step: int) -> str:
    """Staging directory name for a save of ``step`` — deterministic across
    cooperating controller processes (it depends only on the COMMITTED
    manifest, never on scan-time debris): the default ``ckpt_<step>``, or
    ``ckpt_<step>.r1`` when a committed manifest for the same step already
    references the default — the committed payload is never written into
    before the new manifest lands (no torn hybrid on overwrite-same-step)."""
    base = f"ckpt_{int(step)}"
    if os.path.exists(_manifest_path(directory, step)):
        try:
            current = _read_manifest(directory, step).get("payload")
        except Exception:  # noqa: BLE001
            # the committed manifest is unreadable RIGHT NOW (transient blip
            # or torn) — it could reference base OR any .rN, so stage into a
            # name that does not exist on disk at all: the committed payload,
            # whichever it is, is never written into
            cand, k = base, 0
            while os.path.exists(os.path.join(directory, cand)):
                k += 1
                cand = f"{base}.r{k}"
            return cand
        if current == base:
            return base + ".r1"
    return base


# ----------------------------------------------------------------------
# save
# ----------------------------------------------------------------------
def save_checkpoint(directory: str, tree: Any, step: int = 0, keep: int = 3) -> str:
    """Serialize ``tree`` to a manifest-based checkpoint in ``directory``.

    Stages per-leaf payload files (DNDarray leaves as per-host shard files —
    no global gather), then publishes ``ckpt_<step>.manifest.json`` with
    per-file SHA-256 checksums via ``resilience.atomic_write`` — the single
    commit point. Keep-N retention plus a debris sweep run after the commit
    (``keep <= 0`` keeps everything; GC failures degrade to a warning).
    Returns the manifest path.
    """
    from ..core import multihost
    from ..core.dndarray import DNDarray

    step = int(step)
    lost = multihost.lost_peers()
    if lost:
        # a cooperative save cannot commit with a dead peer: its shard files
        # and receipt will never land, and the save/commit barriers would
        # only time out. Fail fast and NAMED — the elastic supervisor's
        # best-effort post-loss commit expects exactly this — and restore
        # from the newest step that verified while the world was whole.
        raise multihost.PeerLostError(
            f"checkpoint save at step {step} aborted: peer process(es) "
            f"{sorted(lost)} lost; a cross-process commit cannot complete",
            peers=lost,
        )
    os.makedirs(directory, exist_ok=True)
    payload_rel = _payload_rel_for_save(directory, step)
    payload_dir = os.path.join(directory, payload_rel)
    os.makedirs(payload_dir, exist_ok=True)
    if multihost.process_count() > 1:  # pragma: no cover - multi-host only
        # drop this host's receipt from any previous crashed attempt FIRST:
        # only checksums published THIS attempt may reach the manifest
        _unlink_quiet(
            os.path.join(payload_dir, f".receipt-{multihost.process_index()}.json")
        )

    paths, leaves, _ = _flatten_with_paths(tree)
    owner = multihost.io_owner()
    _phase("save_begin", step, leaves=len(leaves))
    # phase 1 — MATERIALIZE: everything that may launch a collective
    # (forcing a pending fused chain, allgathering a non-addressable array)
    # runs here, synchronously on every controller, BEFORE any deferred-error
    # file I/O: collective failures surface symmetrically on all hosts, so no
    # host diverges into a collective its peers abandoned mid-loop.
    host_arrays: Dict[int, np.ndarray] = {}
    for i, (pkey, leaf) in enumerate(zip(paths, leaves)):
        if isinstance(leaf, DNDarray):
            if leaf.split is None or leaf.ndim == 0:
                with _T_IO:
                    host_arrays[i] = _to_host_array(leaf.larray)
            else:
                with _T_IO:
                    leaf.parray  # force any pending chain; shard reads stay local
        elif _is_arraylike(leaf):
            arr = _to_host_array(leaf)
            _check_serializable_dtype(arr.dtype, pkey)
            host_arrays[i] = arr
        else:
            _encode_py(leaf)  # unserializable-leaf errors raise symmetrically

    _phase("save_materialized", step)
    # phase 2 — WRITE (local file I/O only). A local failure here must NOT
    # skip the barriers below: the other controllers are (or will be) parked
    # in sync_processes with no timeout, and an early raise would hang the
    # cluster on exactly the flaky-mount failure this subsystem exists to
    # survive. So each phase records its error, every process hits both
    # barriers exactly once, and the error re-raises after. (Scope: a
    # NON-owner cannot learn the owner's commit failed — same
    # no-completion-signal contract as resilience.atomic_write; check
    # latest_step() when that matters.)
    err: Optional[BaseException] = None
    entries: List[dict] = []
    try:
        for i, (pkey, leaf) in enumerate(zip(paths, leaves)):
            base = f"leaf_{i:05d}"
            if isinstance(leaf, DNDarray):
                entry = _save_dndarray(payload_dir, payload_rel, base, leaf, host_arrays.get(i))
            elif _is_arraylike(leaf):
                arr = host_arrays[i]
                fname = f"{base}.arr"
                frag = _file_entry(payload_rel, fname, arr.dtype, arr.shape)
                if owner:  # replicated value: one writer suffices
                    frag["sha256"], frag["bytes"] = _write_payload_file(
                        os.path.join(payload_dir, fname), arr
                    )
                entry = {"kind": "array", "files": [frag]}
            else:
                entry = {"kind": "py", "value": _encode_py(leaf)}
            entry["path"] = pkey
            entries.append(entry)
    except BaseException as exc:  # noqa: BLE001 - re-raised after the barriers
        err = exc

    # multi-controller only: each host publishes a RECEIPT of the shard
    # checksums it wrote THIS attempt. The owner fills peer frags from
    # receipts, never by hashing whatever file sits at the path — a host
    # whose writes failed produces no receipt, so a stale same-name shard
    # left by a previous crashed attempt can never be checksummed into a
    # "verified" hybrid manifest.
    if err is None and multihost.process_count() > 1:  # pragma: no cover - multi-host
        try:
            receipt = {
                frag["file"]: [frag["sha256"], frag["bytes"]]
                for entry in entries
                for frag in entry.get("files", ())
                if frag["sha256"] is not None
            }
            rpath = os.path.join(payload_dir, f".receipt-{multihost.process_index()}.json")

            def _publish_receipt():
                tmp = f"{rpath}.tmp-{os.getpid()}-{_proc()}"
                try:
                    with open(tmp, "w") as fh:
                        json.dump(receipt, fh)
                    os.replace(tmp, rpath)
                except BaseException:
                    _unlink_quiet(tmp)
                    raise

            resilience.call_with_retries("checkpoint.write", _publish_receipt)
        except BaseException as exc:  # noqa: BLE001 - re-raised after the barriers
            err = exc

    _phase("save_staged", step, leaves=len(entries))
    # every host's shard files (and receipts) must be on the (shared)
    # filesystem before the owner builds the manifest it is about to publish
    multihost.sync_processes(f"heat_tpu.checkpoint.save.{step}")

    manifest_path = _manifest_path(directory, step)
    if owner and err is None:
        try:
            needed = [
                frag
                for entry in entries
                for frag in entry.get("files", ())
                if frag["sha256"] is None  # written (or not) by another host
            ]
            if needed:  # pragma: no cover - multi-host only
                peer_receipts: Dict[str, list] = {}
                for p in range(multihost.process_count()):
                    rpath = os.path.join(payload_dir, f".receipt-{p}.json")

                    def _read_receipt(rp=rpath):
                        with open(rp) as fh:
                            return json.load(fh)

                    try:
                        peer_receipts.update(
                            resilience.call_with_retries("checkpoint.restore", _read_receipt)
                        )
                    except FileNotFoundError:
                        pass  # that host failed its writes: its frags stay unfilled
                for frag in needed:
                    if frag["file"] not in peer_receipts:
                        raise RuntimeError(
                            f"shard {frag['file']} was never published this attempt "
                            "(a peer controller's write failed) — refusing to commit "
                            "a manifest referencing stale bytes"
                        )
                    frag["sha256"], frag["bytes"] = peer_receipts[frag["file"]]
            doc = {
                "format": _FORMAT_NAME,
                "version": MANIFEST_VERSION,
                "step": step,
                "payload": payload_rel,
                "leaves": entries,
            }

            def _commit():
                with resilience.atomic_write(manifest_path) as tmp:
                    with open(tmp, "w") as fh:
                        json.dump(doc, fh, indent=1)
                        fh.write("\n")

            resilience.call_with_retries("checkpoint.commit", _commit)
            telemetry.record_checkpoint("save", step)
            _phase("save_committed", step)
        except BaseException as exc:  # noqa: BLE001 - re-raised after the barrier
            err = exc
    # non-owners wait for the commit so no controller returns (and possibly
    # restores) before the manifest exists
    multihost.sync_processes(f"heat_tpu.checkpoint.commit.{step}")
    if err is not None:
        raise err
    gc_checkpoints(directory, keep=keep, protect_step=step)
    return manifest_path


# ----------------------------------------------------------------------
# verification
# ----------------------------------------------------------------------
def verify_checkpoint(directory: str, step: int) -> List[str]:
    """Verify the committed checkpoint for ``step``; returns the list of
    problems (empty == the checkpoint verifies).

    Manifest checkpoints: the manifest must parse, every referenced payload
    file must exist with a matching size and SHA-256. Legacy msgpack blobs:
    the msgpack stream must decode (truncation is the crash signature).
    """
    step = int(step)
    return _verify_step(directory, step)


def _verify_step(directory: str, step: int, keep_probe: bool = False) -> List[str]:
    if os.path.exists(_manifest_path(directory, step)):
        return _verify_manifest_artifact(directory, step)
    if os.path.exists(_legacy_path(directory, step)):
        return _verify_legacy_artifact(directory, step, keep_probe=keep_probe)
    return [f"no committed checkpoint for step {step}"]


def _verify_manifest_artifact(directory: str, step: int) -> List[str]:
    try:
        doc = _read_manifest(directory, step)
    except Exception as exc:  # noqa: BLE001 - any parse failure = torn manifest
        return [f"manifest unreadable: {exc!r}"]
    if doc.get("format") != _FORMAT_NAME:
        return [f"manifest format {doc.get('format')!r} is not {_FORMAT_NAME!r}"]
    if int(doc.get("version", -1)) > MANIFEST_VERSION:
        return [f"manifest version {doc.get('version')} is newer than supported {MANIFEST_VERSION}"]
    problems = []
    for entry in doc.get("leaves", ()):
        for frag in entry.get("files", ()):
            full = os.path.join(directory, frag["file"])
            try:
                # one retried stat covers existence AND size: a transient
                # EIO must ride the same retry/fallback path as the hash
                # reads, not abort the whole load uncaught
                size = resilience.call_with_retries(
                    "checkpoint.restore", os.path.getsize, full
                )
            except FileNotFoundError:
                problems.append(f"missing payload file {frag['file']}")
                continue
            except OSError as exc:
                problems.append(f"payload file {frag['file']} unreadable: {exc!r}")
                continue
            if frag.get("bytes") is not None and size != frag["bytes"]:
                problems.append(
                    f"payload file {frag['file']} is {size} bytes, "
                    f"manifest says {frag['bytes']}"
                )
                continue
            try:
                if frag.get("sha256") and _sha256_file(full) != frag["sha256"]:
                    problems.append(f"payload file {frag['file']} fails its SHA-256 check")
            except OSError as exc:
                problems.append(f"payload file {frag['file']} unreadable: {exc!r}")
    return problems


#: one-slot (path, mtime, size) -> decoded state memo: the LOAD path's verify
#: already reads and msgpack-decodes the whole legacy blob, so the restore
#: that follows immediately must not pay the full read+decode a second time.
#: Only populated with ``keep_probe=True`` (the load path) — a bare public
#: ``verify_checkpoint()`` or a GC validity scan must not pin a potentially
#: multi-GB decoded state in module state for the life of the process.
_LEGACY_PROBE: Optional[Tuple[str, float, int, Any]] = None


def _legacy_stat(path: str) -> Tuple[float, int]:
    st = os.stat(path)
    return st.st_mtime, st.st_size


def _verify_legacy_artifact(directory: str, step: int, keep_probe: bool = False) -> List[str]:
    from flax import serialization

    global _LEGACY_PROBE
    lpath = _legacy_path(directory, step)

    def _probe():
        with open(lpath, "rb") as fh:
            return fh.read()

    try:
        stat = _legacy_stat(lpath)
        state = serialization.msgpack_restore(
            resilience.call_with_retries("checkpoint.restore", _probe)
        )
    except Exception as exc:  # noqa: BLE001 - any decode failure = torn blob
        _LEGACY_PROBE = None
        return [f"legacy msgpack undecodable (truncated/corrupt): {exc!r}"]
    if keep_probe:
        _LEGACY_PROBE = (lpath, stat[0], stat[1], state)
    return []


# ----------------------------------------------------------------------
# restore
# ----------------------------------------------------------------------
def _read_array_file(directory: str, frag: dict) -> np.ndarray:
    full = os.path.join(directory, frag["file"])
    dtype = _np_dtype(frag["dtype"])
    shape = tuple(frag["shape"])

    def _read():
        if frag["format"] == "npy":
            return np.load(full, allow_pickle=False)
        return np.fromfile(full, dtype=dtype).reshape(shape)

    arr = resilience.call_with_retries("checkpoint.restore", _read)
    if tuple(arr.shape) != shape:
        raise CheckpointCorruptError(
            f"payload file {frag['file']} holds shape {tuple(arr.shape)}, manifest says {shape}"
        )
    return arr


def _open_array_lazy(directory: str, frag: dict):
    """Memory-mapped view of a payload file — per-range reads only page in
    the requested blocks (the elastic-restore path never assembles the
    global array on the host)."""
    full = os.path.join(directory, frag["file"])
    if frag["format"] == "npy":
        return np.load(full, mmap_mode="r", allow_pickle=False)
    return np.memmap(full, dtype=_np_dtype(frag["dtype"]), mode="r", shape=tuple(frag["shape"]))


def _restore_dndarray(directory: str, entry: dict, template) -> Any:
    """Elastic DNDarray restore: reshard the saved per-rank shard files onto
    the CURRENT topology — the template's comm/device AND split (or the
    default comm with the saved split), bitwise identical to the saved
    global array. Neither the mesh size nor the split axis needs to match
    the save-time layout: any requested global block is assembled from the
    overlapping saved shards' ranges along the SAVED split axis (arxiv
    2112.01075 frames restore-onto-a-different-mesh as exactly this
    redistribution problem)."""
    from ..core import devices as devices_module
    from ..core import factories, io as io_module, types
    from ..core.communication import sanitize_comm
    from ..core.dndarray import DNDarray

    gshape = tuple(int(s) for s in entry["gshape"])
    saved_split = entry["split"]
    dtype = types.canonical_heat_type(_np_dtype(entry["dtype"]))
    out_split = saved_split
    if isinstance(template, DNDarray):
        comm, device = template.comm, template.device
        out_split = template.split  # the template names the layout wanted NOW
        if tuple(template.shape) != gshape:
            raise ValueError(
                f"checkpoint leaf {entry['path']!r} has global shape {gshape}, "
                f"target template has {tuple(template.shape)}"
            )
    else:
        comm, device = sanitize_comm(None), devices_module.sanitize_device(None)
    if saved_split is None or not gshape:
        arr = _read_array_file(directory, entry["files"][0])
        return factories.array(arr, dtype=dtype, split=out_split, device=device, comm=comm)
    saved_split = int(saved_split) % len(gshape)
    # open every shard's lazy handle ONCE — read_block runs per target
    # device, and reopening mmaps O(devices x shards) times would multiply
    # open+header-parse round trips on the network filesystems this targets
    shards = [
        (frag["start"], frag["stop"], _open_array_lazy(directory, frag))
        for frag in sorted(
            (f for f in entry["files"] if f.get("rank") is not None),
            key=lambda f: f["start"],
        )
    ]

    def read_block(sl):
        # general global-slice read: intersect the requested range along the
        # SAVED split with each shard (other dims pass through), so the
        # target layout may slice along ANY axis, not just the saved one.
        # _sharded_ingest hands open slice(None)s for untouched dims —
        # normalize to concrete bounds first.
        sl = tuple(
            slice(s.start or 0, gshape[d] if s.stop is None else s.stop)
            for d, s in enumerate(sl)
        )
        lo, hi = sl[saved_split].start, sl[saved_split].stop
        pieces = []
        for start, stop, mm in shards:
            s, e = max(lo, start), min(hi, stop)
            if s < e:
                idx = list(sl)
                idx[saved_split] = slice(s - start, e - start)
                pieces.append(np.asarray(mm[tuple(idx)]))
        if not pieces:
            shape = [sl[d].stop - sl[d].start for d in range(len(gshape))]
            shape[saved_split] = 0
            return np.empty(tuple(shape), dtype=_np_dtype(entry["dtype"]))
        return pieces[0] if len(pieces) == 1 else np.concatenate(pieces, axis=saved_split)

    if out_split is None:
        # same retry contract as the _sharded_ingest page-ins below: the
        # mmap reads inside read_block hit the (possibly flaky) filesystem
        full = resilience.call_with_retries(
            "checkpoint.restore", read_block, tuple(slice(0, s) for s in gshape)
        )
        return factories.array(full, dtype=dtype, split=None, device=device, comm=comm)
    with memledger.owner_scope("checkpoint"):
        # restore staging buffers (the ingest's per-device pieces) attribute
        # to "checkpoint" in the live-buffer ledger — a watermark sample
        # taken mid-restore names this subsystem, not "unattributed"
        return io_module._sharded_ingest(
            read_block, gshape, dtype, int(out_split) % len(gshape), device, comm
        )


def _restore_manifest(directory: str, step: int, target: Any) -> Any:
    from ..core.dndarray import DNDarray

    doc = _read_manifest(directory, step)
    paths, leaves, treedef = _flatten_with_paths(target)
    by_path = {e["path"]: e for e in doc.get("leaves", ())}
    if sorted(by_path) != sorted(paths):
        missing = sorted(set(paths) - set(by_path))
        extra = sorted(set(by_path) - set(paths))
        raise ValueError(
            f"checkpoint step {step} does not match the target structure: "
            f"missing from checkpoint {missing[:5]}, not in target {extra[:5]}"
        )
    out = []
    for pkey, tleaf in zip(paths, leaves):
        entry = by_path[pkey]
        kind = entry["kind"]
        if kind == "py":
            out.append(_decode_py(entry["value"]))
        elif kind == "array":
            arr = _read_array_file(directory, entry["files"][0])
            tshape = getattr(tleaf, "shape", None)
            if tshape is not None and tuple(tshape) != tuple(arr.shape):
                raise ValueError(
                    f"checkpoint leaf {pkey!r} has shape {tuple(arr.shape)}, "
                    f"target template has {tuple(tshape)}"
                )
            out.append(arr)
        elif kind == "dndarray":
            out.append(_restore_dndarray(directory, entry, tleaf))
        else:
            raise CheckpointCorruptError(
                f"checkpoint step {step} in {directory!r}: unknown leaf kind {kind!r}"
            )
    telemetry.record_checkpoint("restore", step)
    _phase("restore_done", step, leaves=len(out))
    return jax.tree_util.tree_unflatten(treedef, out)


def _restore_legacy_file(path: str, label: str, target: Any) -> Any:
    """Read + msgpack-decode + reconstruct one legacy blob at ``path`` (the
    load-path probe memo skips the read+decode when verify just did it);
    every failure surfaces as :class:`CheckpointCorruptError` naming the
    file, never a cryptic flax deserialization error."""
    from flax import serialization

    global _LEGACY_PROBE
    try:
        state = None
        probe, _LEGACY_PROBE = _LEGACY_PROBE, None
        if probe is not None and probe[0] == path and _legacy_stat(path) == probe[1:3]:
            state = probe[3]  # verify just decoded this exact file
        if state is None:

            def _read():
                with open(path, "rb") as fh:
                    return fh.read()

            state = serialization.msgpack_restore(
                resilience.call_with_retries("checkpoint.restore", _read)
            )
        return serialization.from_state_dict(target, state)
    except Exception as exc:  # noqa: BLE001 - flax raises format-dependent types
        raise CheckpointCorruptError(
            f"legacy checkpoint {path!r} ({label}) failed to deserialize "
            f"({exc!r}) — truncated/corrupt msgpack, or a target-structure "
            "mismatch; no fallback taken"
        ) from exc


def _restore_legacy(directory: str, step: int, target: Any) -> Any:
    restored = _restore_legacy_file(_legacy_path(directory, step), f"step {step}", target)
    telemetry.record_checkpoint("restore", step)
    return restored


def _restore_step(directory: str, step: int, target: Any) -> Any:
    _phase("restore_begin", step)
    if os.path.exists(_manifest_path(directory, step)):
        return _restore_manifest(directory, step, target)
    return _restore_legacy(directory, step, target)


def load_checkpoint(
    directory: str, target: Any, step: Optional[int] = None, strict: bool = False
) -> Any:
    """Restore a checkpoint into the structure of ``target``.

    ``target`` is a template pytree (e.g. a freshly-initialized state dict);
    its leaves' shapes validate the restore, DNDarray template leaves select
    elastic restore onto their comm/device. ``step=None`` loads the newest
    checkpoint **that verifies** — unverifiable newer checkpoints emit a
    :class:`CheckpointCorruptWarning` and are skipped (``strict=True`` raises
    :class:`CheckpointCorruptError` instead of falling back). An explicit
    ``step=`` that does not exist raises ``FileNotFoundError`` listing the
    available steps; an explicit step that exists but fails verification
    raises :class:`CheckpointCorruptError` (no fallback — you asked for that
    one). A direct manifest/msgpack file path is accepted as ``directory``.
    """
    if os.path.isfile(directory):
        name = os.path.basename(directory)
        parent = os.path.dirname(directory) or "."
        is_manifest = _MANIFEST_RE.match(name) is not None
        m = _MANIFEST_RE.match(name) or _LEGACY_RE.match(name)
        if m is None:
            # the original API accepted ANY file path as a msgpack blob
            # (renamed/copied checkpoints, `cp ckpt_100.msgpack best.msgpack`);
            # keep that contract — decode failures surface as the same
            # CheckpointCorruptError, and the decode IS the verification
            restored = _restore_legacy_file(directory, "explicit file path", target)
            telemetry.record_checkpoint("restore")
            return restored
        file_step = int(m.group(1))
        # verify and restore the artifact the caller NAMED — an explicit
        # legacy path must not resolve to a manifest sibling of the same step
        if is_manifest:
            problems = _verify_manifest_artifact(parent, file_step)
        else:
            problems = _verify_legacy_artifact(parent, file_step, keep_probe=True)
        if problems:
            telemetry.record_checkpoint("corrupt", file_step)
            raise CheckpointCorruptError(
                f"checkpoint {directory!r} (step {file_step}) failed verification: "
                f"{'; '.join(problems[:3])} — no fallback (explicit file path given)"
            )
        restore = _restore_manifest if is_manifest else _restore_legacy
        return restore(parent, file_step, target)

    steps = _all_steps(directory)
    if not steps:
        raise FileNotFoundError(f"no checkpoints in {directory!r}")
    if step is not None:
        step = int(step)
        if step not in steps:
            raise FileNotFoundError(
                f"no checkpoint for step {step} in {directory!r}; "
                f"available steps: {steps}"
            )
        problems = _verify_step(directory, step, keep_probe=True)
        if problems:
            telemetry.record_checkpoint("corrupt", step)
            raise CheckpointCorruptError(
                f"checkpoint step {step} in {directory!r} failed verification: "
                f"{'; '.join(problems[:3])} — no fallback (explicit step= requested)"
            )
        return _restore_step(directory, step, target)

    skipped: List[Tuple[int, List[str]]] = []
    for s in reversed(steps):
        problems = _verify_step(directory, s, keep_probe=True)
        if not problems:
            if skipped:
                telemetry.record_checkpoint("fallback", s)
                warnings.warn(
                    CheckpointCorruptWarning(
                        f"checkpoint step(s) {[t for t, _ in skipped]} in {directory!r} "
                        f"failed verification ({skipped[0][1][0]}); falling back to the "
                        f"newest checkpoint that verifies: step {s}"
                    ),
                    stacklevel=2,
                )
            return _restore_step(directory, s, target)
        telemetry.record_checkpoint("corrupt", s)
        if strict:
            raise CheckpointCorruptError(
                f"checkpoint step {s} in {directory!r} failed verification: "
                f"{'; '.join(problems[:3])} — strict=True forbids falling back "
                f"to an older checkpoint (available steps: {steps})"
            )
        skipped.append((s, problems))
    raise CheckpointCorruptError(
        f"no checkpoint in {directory!r} verifies — tried steps "
        f"{[t for t, _ in skipped]}; newest failure: {skipped[0][1][:3]}"
    )


# ----------------------------------------------------------------------
# retention + debris GC
# ----------------------------------------------------------------------
def gc_checkpoints(directory: str, keep: int = 3, protect_step: Optional[int] = None) -> None:
    """Validity-aware keep-N retention plus a debris sweep.

    Deletes committed checkpoints beyond the newest ``keep`` (``keep <= 0``
    skips retention), but NEVER the last checkpoint that verifies: when none
    of the kept steps verifies, the newest verifiable older checkpoint is
    protected instead of culled. Sweeps orphaned temp/staging debris —
    legacy ``ckpt_*.msgpack.tmp``, ``*.tmp-*`` staging files, payload
    directories no committed manifest references — that is older than the
    newest committed manifest (an in-flight save's staging is never younger
    than the newest commit by less than a rename). Only the I/O-owning
    process deletes; any failure degrades to a warning and leaves the rest
    for the next sweep (``checkpoint.gc`` fault site).
    """
    from ..core import multihost

    if not multihost.io_owner():
        return  # pragma: no cover - multi-host only
    try:
        swept = _gc_inner(directory, keep, protect_step)
        if swept:
            telemetry.record_checkpoint("gc", protect_step, detail=f"removed {swept}")
    except Exception as exc:  # noqa: BLE001 - GC must never fail the save
        warnings.warn(
            f"checkpoint GC in {directory!r} failed ({exc!r}); "
            "debris left for the next sweep",
            stacklevel=2,
        )


def _gc_remove(path: str, tree: bool = False) -> bool:
    try:
        if resilience._ARMED:
            resilience.check("checkpoint.gc")
        if tree:
            shutil.rmtree(path)
        else:
            os.remove(path)
        return True
    except OSError:
        return False  # transient/injected: the next sweep gets it


def _gc_inner(directory: str, keep: int, protect_step: Optional[int]) -> int:
    if resilience._ARMED:
        # one check at sweep entry (plus one per deletion below): an armed
        # gc fault exercises the degrade path even when nothing is deletable
        resilience.check("checkpoint.gc")
    committed = _committed(directory)
    steps = sorted(committed)
    swept = 0

    # --- keep-N retention, validity-aware -----------------------------
    protect_valid: Optional[int] = None
    if keep > 0 and len(steps) > keep:
        kept, doomed = steps[-keep:], steps[:-keep]
        # the step just committed by the enclosing save verifies by
        # construction — skip re-hashing the whole kept window for it
        kept_has_valid = protect_step in kept or any(
            not verify_checkpoint(directory, s) for s in reversed(kept)
        )
        if not kept_has_valid:
            # the whole kept window is unverifiable: protect the newest
            # older checkpoint that verifies — never delete the last good one
            for s in reversed(doomed):
                if not verify_checkpoint(directory, s):
                    protect_valid = s
                    break
        for s in doomed:
            if s == protect_step or s == protect_valid:
                continue
            swept += _delete_step(directory, s)

    # --- debris sweep -------------------------------------------------
    manifest_mtimes = []
    referenced = set()
    unreadable_steps = set()
    for s, name in _committed(directory).items():
        if _MANIFEST_RE.match(name):
            full = os.path.join(directory, name)
            try:
                manifest_mtimes.append(os.path.getmtime(full))
                referenced.add(_read_manifest(directory, s).get("payload"))
            except Exception:  # noqa: BLE001
                # a manifest unreadable RIGHT NOW (transient mount blip — or
                # genuinely torn, indistinguishable from here) may still
                # reference its step's payload: protect every payload dir of
                # that step rather than rmtree a committed checkpoint's data
                # on a flaky read; retention removes torn steps explicitly
                unreadable_steps.add(s)
    if not manifest_mtimes:
        return swept
    newest = max(manifest_mtimes)

    def _older(path: str) -> bool:
        try:
            return os.path.getmtime(path) < newest
        except OSError:
            return False

    for name in sorted(os.listdir(directory)):
        full = os.path.join(directory, name)
        if os.path.isdir(full):
            m = _PAYLOAD_RE.match(name)
            if (
                m
                and name not in referenced
                and int(m.group(1)) not in unreadable_steps
                and _older(full)
            ):
                swept += _gc_remove(full, tree=True)  # uncommitted staging / orphan
            elif name in referenced:
                # stale staging temps inside a LIVE payload dir (a crashed
                # attempt that reused the directory): sweep just the temps
                for sub in os.listdir(full):
                    subfull = os.path.join(full, sub)
                    if ".tmp-" in sub and _older(subfull):
                        swept += _gc_remove(subfull)
        elif (_LEGACY_TMP_RE.match(name) or ".tmp-" in name) and _older(full):
            swept += _gc_remove(full)  # crash-orphaned temp files
    return swept


def _delete_step(directory: str, step: int) -> int:
    """Delete one committed checkpoint crash-consistently: any legacy blob
    first (the manifest, which wins step resolution, still commits the step),
    then the manifest — the commit point: the checkpoint becomes invisible —
    and only once THAT unlink succeeded, its payload directory. A failure or
    crash at any point leaves a still-committed checkpoint intact or
    unreferenced debris for the next sweep — never a committed manifest
    whose payload is gone, and never a step resurrecting as stale legacy
    data."""
    removed = 0
    lpath = _legacy_path(directory, step)
    if os.path.exists(lpath):
        if not _gc_remove(lpath):
            return removed  # retry next sweep; the step stays fully intact
        removed += 1
    mpath = _manifest_path(directory, step)
    if os.path.exists(mpath):
        try:
            payload = _read_manifest(directory, step).get("payload")
        except Exception:  # noqa: BLE001 - torn manifest: still delete it
            payload = None
        if not _gc_remove(mpath):
            return removed  # still committed: its payload must not be touched
        removed += 1
        if payload:
            full = os.path.join(directory, payload)
            if os.path.isdir(full):
                removed += _gc_remove(full, tree=True)
    return removed

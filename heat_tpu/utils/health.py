"""Mesh health / failure detection utilities.

The reference's failure story is MPI's: a dead rank aborts the job and
SLURM restarts it (SURVEY.md §5 — no in-framework detection). On TPU the
failure modes are different — a tunnel/backend can hang rather than die —
so this module gives the runtime an explicit health surface:

* :func:`ping_mesh` — one tiny psum over every mesh device with a wall-clock
  budget, returning status + latency (run in a worker thread so a hung
  backend cannot hang the caller).
* :func:`assert_mesh_healthy` — raise if the mesh does not answer in time.
* :func:`memory_report` — live device-buffer bytes per device (leak triage).
"""

from __future__ import annotations

import queue
import threading
import time
from typing import Optional

import jax
import jax.numpy as jnp

from ..core.communication import MeshCommunication, sanitize_comm

__all__ = ["ping_mesh", "assert_mesh_healthy", "memory_report"]


class MeshUnhealthyError(RuntimeError):
    """The device mesh failed to answer a collective within the budget."""


def _ping(comm: MeshCommunication) -> float:
    """One tiny all-device psum; returns the observed wall latency."""
    from jax.sharding import PartitionSpec as P

    start = time.perf_counter()
    x = jax.device_put(
        jnp.arange(comm.size, dtype=jnp.float32), comm.sharding(1, 0)
    )
    fn = jax.jit(
        jax.shard_map(
            lambda s: jax.lax.psum(s, comm.axis_name),
            mesh=comm.mesh,
            in_specs=P(comm.axis_name),
            out_specs=P(comm.axis_name),
            check_vma=False,
        )
    )
    out = fn(x)
    total = float(jnp.sum(out))  # host sync
    expect = float(comm.size) * sum(range(comm.size))
    if total != expect:
        raise MeshUnhealthyError(
            f"collective returned {total}, expected {expect} — mesh state corrupt"
        )
    return time.perf_counter() - start


def ping_mesh(comm: Optional[MeshCommunication] = None, timeout: float = 60.0) -> dict:
    """Probe the mesh with one collective under a wall-clock budget.

    Returns ``{"ok", "latency_s", "devices", "platform", "error"}``. A hung
    backend (the axon tunnel's observed failure mode) yields ``ok=False``
    with ``error="timeout"`` instead of hanging the caller — the probe runs
    in a worker thread.
    """
    comm = sanitize_comm(comm)
    info = {
        "ok": False,
        "latency_s": None,
        "devices": comm.size,
        "platform": comm.devices[0].platform if comm.devices else "?",
        "error": None,
    }
    # a DAEMON thread, not an executor: ThreadPoolExecutor.shutdown (and the
    # interpreter's atexit join of its non-daemon workers) would block on a
    # hung backend — the exact failure this probe exists to bound
    result: "queue.Queue" = queue.Queue(maxsize=1)

    def run():
        try:
            result.put(("ok", _ping(comm)))
        except Exception as exc:  # noqa: BLE001
            result.put(("err", f"{type(exc).__name__}: {exc}"))

    t = threading.Thread(target=run, daemon=True)
    t.start()
    try:
        kind, val = result.get(timeout=timeout)
    except queue.Empty:
        info["error"] = "timeout"
        return info
    if kind == "ok":
        info["latency_s"] = round(val, 6)
        info["ok"] = True
    else:
        info["error"] = val
    return info


def assert_mesh_healthy(comm: Optional[MeshCommunication] = None, timeout: float = 60.0) -> dict:
    """Raise :class:`MeshUnhealthyError` unless :func:`ping_mesh` succeeds."""
    info = ping_mesh(comm, timeout=timeout)
    if not info["ok"]:
        raise MeshUnhealthyError(f"mesh health probe failed: {info}")
    return info


def memory_report(comm: Optional[MeshCommunication] = None, top: int = 5) -> dict:
    """Live device-buffer bytes per device of ``comm``'s mesh, from
    ``jax.live_arrays()`` — the leak-triage companion of the reference's
    (non-existent) memory tooling; exceeds reference scope like
    utils/profiling does.

    Buffers are deduped with the ledger's own key (``memledger._buffer_key``
    — (device, buffer pointer), so the two surfaces can never disagree on
    what "one buffer" is), meaning a buffer addressable from multiple
    shards is never double-counted; deleted/donated arrays are
    skipped via ``is_deleted()`` plus the narrow ``RuntimeError`` the racing
    shards read raises — no blanket except. Returns ``total_bytes``,
    ``per_device_bytes``, the deduped ``buffer_count`` and the ``top``-K
    largest buffers (shape/dtype/bytes, owner-attributed via the
    ``core/memledger`` registry)."""
    from ..core import memledger

    comm = sanitize_comm(comm)
    mesh_devices = {str(d) for d in comm.devices}
    per_device: dict = {}
    total = 0
    buffer_count = 0
    seen: set = set()
    largest: list = []
    # attributed arrays claim their buffers first (same ordering rule as
    # memledger._scan): a global sharded array and its per-shard children
    # are BOTH live arrays over the same device buffers, and the dedupe
    # must not let enumeration order hand the bytes to an untagged child
    ranked = sorted(
        jax.live_arrays(),
        key=lambda arr: memledger._owner_of(arr) == memledger.UNATTRIBUTED,
    )
    for arr in ranked:
        try:
            if arr.is_deleted():
                continue
            shards = arr.addressable_shards
        except RuntimeError:  # deleted/donated between the check and the read
            continue
        arr_bytes = 0
        for i, s in enumerate(shards):
            key = str(s.device)
            if key not in mesh_devices:
                continue
            ident = memledger._buffer_key(s, arr, i)
            if ident in seen:
                continue
            seen.add(ident)
            try:
                nbytes = int(s.data.nbytes)
            except RuntimeError:  # deleted mid-walk
                continue
            per_device[key] = per_device.get(key, 0) + nbytes
            total += nbytes
            arr_bytes += nbytes
            buffer_count += 1
        if arr_bytes:
            largest.append(
                (
                    arr_bytes,
                    {
                        "nbytes": arr_bytes,
                        "shape": [int(d) for d in arr.shape],
                        "dtype": str(arr.dtype),
                        "owner": memledger._owner_of(arr),
                    },
                )
            )
    largest.sort(key=lambda t: -t[0])
    return {
        "total_bytes": total,
        "per_device_bytes": per_device,
        "buffer_count": buffer_count,
        "top_buffers": [rec for _, rec in largest[: max(0, int(top))]],
    }

"""Mesh health / failure detection utilities.

The reference's failure story is MPI's: a dead rank aborts the job and
SLURM restarts it (SURVEY.md §5 — no in-framework detection). On TPU the
failure modes are different — a tunnel/backend can hang rather than die —
so this module gives the runtime an explicit health surface:

* :func:`ping_mesh` — one tiny psum over every mesh device with a wall-clock
  budget, returning status + latency (run in a worker thread so a hung
  backend cannot hang the caller).
* :func:`assert_mesh_healthy` — raise if the mesh does not answer in time.
* :func:`memory_report` — live device-buffer bytes per device (leak triage).
"""

from __future__ import annotations

import concurrent.futures
import time
from typing import Optional

import jax
import jax.numpy as jnp

from ..core.communication import MeshCommunication, sanitize_comm

__all__ = ["ping_mesh", "assert_mesh_healthy", "memory_report"]


class MeshUnhealthyError(RuntimeError):
    """The device mesh failed to answer a collective within the budget."""


def _ping(comm: MeshCommunication) -> float:
    """One tiny all-device psum; returns the observed wall latency."""
    from jax.sharding import PartitionSpec as P

    start = time.perf_counter()
    x = jax.device_put(
        jnp.arange(comm.size, dtype=jnp.float32), comm.sharding(1, 0)
    )
    fn = jax.jit(
        jax.shard_map(
            lambda s: jax.lax.psum(s, comm.axis_name),
            mesh=comm.mesh,
            in_specs=P(comm.axis_name),
            out_specs=P(comm.axis_name),
            check_vma=False,
        )
    )
    out = fn(x)
    total = float(jnp.sum(out))  # host sync
    expect = float(comm.size) * sum(range(comm.size))
    if total != expect:
        raise MeshUnhealthyError(
            f"collective returned {total}, expected {expect} — mesh state corrupt"
        )
    return time.perf_counter() - start


def ping_mesh(comm: Optional[MeshCommunication] = None, timeout: float = 60.0) -> dict:
    """Probe the mesh with one collective under a wall-clock budget.

    Returns ``{"ok", "latency_s", "devices", "platform", "error"}``. A hung
    backend (the axon tunnel's observed failure mode) yields ``ok=False``
    with ``error="timeout"`` instead of hanging the caller — the probe runs
    in a worker thread.
    """
    comm = sanitize_comm(comm)
    info = {
        "ok": False,
        "latency_s": None,
        "devices": comm.size,
        "platform": comm.devices[0].platform if comm.devices else "?",
        "error": None,
    }
    with concurrent.futures.ThreadPoolExecutor(max_workers=1) as pool:
        fut = pool.submit(_ping, comm)
        try:
            info["latency_s"] = round(fut.result(timeout=timeout), 6)
            info["ok"] = True
        except concurrent.futures.TimeoutError:
            info["error"] = "timeout"
        except Exception as exc:  # noqa: BLE001
            info["error"] = f"{type(exc).__name__}: {exc}"
    return info


def assert_mesh_healthy(comm: Optional[MeshCommunication] = None, timeout: float = 60.0) -> dict:
    """Raise :class:`MeshUnhealthyError` unless :func:`ping_mesh` succeeds."""
    info = ping_mesh(comm, timeout=timeout)
    if not info["ok"]:
        raise MeshUnhealthyError(f"mesh health probe failed: {info}")
    return info


def memory_report(comm: Optional[MeshCommunication] = None) -> dict:
    """Live device-buffer bytes per device (and total), from
    ``jax.live_arrays()`` — the leak-triage companion of the reference's
    (non-existent) memory tooling; exceeds reference scope like
    utils/profiling does."""
    comm = sanitize_comm(comm)
    per_device: dict = {}
    total = 0
    for arr in jax.live_arrays():
        try:
            shards = arr.addressable_shards
        except Exception:  # pragma: no cover - deleted/donated buffers
            continue
        for s in shards:
            nbytes = int(np_prod(s.data.shape) * s.data.dtype.itemsize)
            key = str(s.device)
            per_device[key] = per_device.get(key, 0) + nbytes
            total += nbytes
    return {"total_bytes": total, "per_device_bytes": per_device}


def np_prod(shape) -> int:
    out = 1
    for s in shape:
        out *= int(s)
    return out

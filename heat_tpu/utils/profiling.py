"""Tracing / profiling subsystem.

The reference has no built-in profiling — its benchmarks hand-time with
``time.perf_counter`` (reference benchmarks/kmeans/heat-cpu.py:22-26) and
SURVEY.md §5 calls for ``jax.profiler`` traces as the first-class TPU
replacement. This module provides:

* :func:`trace` — context manager writing an XLA/TensorBoard trace directory
  (open with ``tensorboard --logdir`` or xprof) covering everything the
  enclosed code dispatches, including pallas kernels and ICI collectives.
* :func:`annotate` — named region that shows up inside device traces
  (``jax.profiler.TraceAnnotation``); usable as decorator or context manager.
* :class:`Timer` / :func:`timed` — a process-local registry of wall-clock
  timers that synchronize on device results (``block_until_ready``), so a
  timed region measures compute, not dispatch.
* :func:`report` — aggregate {name: {calls, total_s, mean_s, best_s}}.
* :func:`device_memory_stats` — per-device live-bytes snapshot where the
  backend exposes it (TPU does; forced-host CPU returns {}).
* :func:`host_memory_stats` — current/peak RSS + physical total of THIS
  process's host, the fallback memory surface on CPU meshes (and the
  denominator for fractional ``HEAT_TPU_MEMORY_BUDGET`` specs there).
"""

from __future__ import annotations

import contextlib
import functools
import os
import time
from typing import Any, Callable, Dict, Optional

import jax

# module-level, not per-call: record_timing sits on the Timer hot path and
# core.telemetry has no module-level dependency back on utils (no cycle)
from heat_tpu.core import telemetry as _telemetry

__all__ = [
    "Timer",
    "annotate",
    "device_memory_stats",
    "host_memory_stats",
    "record_timing",
    "report",
    "reset",
    "timed",
    "trace",
]


@contextlib.contextmanager
def trace(log_dir: str, create_perfetto_link: bool = False):
    """Write a device+host profiler trace of the enclosed block to ``log_dir``."""
    jax.profiler.start_trace(log_dir, create_perfetto_link=create_perfetto_link)
    try:
        yield log_dir
    finally:
        jax.profiler.stop_trace()


def annotate(name: str):
    """Named trace region: ``with annotate("lloyd_step"): ...`` or as a
    decorator. Regions nest and appear on the device timeline."""
    return jax.profiler.TraceAnnotation(name)


class Timer:
    """Wall-clock timer that blocks on device work before stopping.

    >>> with Timer("assign"):           # records into the global registry
    ...     out = step(x)               # result synced automatically if returned
    """

    _registry: Dict[str, Dict[str, Any]] = {}

    def __init__(self, name: str, sync: bool = True):
        self.name = name
        self.sync = sync
        self._start = None
        self.elapsed = 0.0

    def __enter__(self) -> "Timer":
        self._start = time.perf_counter()
        return self

    def __exit__(self, *exc) -> None:
        if self.sync and exc == (None, None, None):
            _sync_all_devices()
        self.elapsed = time.perf_counter() - self._start
        record_timing(self.name, self.elapsed)


def record_timing(name: str, elapsed: float) -> None:
    """Record one completed timing into the registry (the shared path for
    ``Timer`` and ``heat_tpu.telemetry.span``). Active telemetry spans absorb
    timers closing inside them (``ht.telemetry.span`` nesting contract), and
    in verbose mode every close lands on the trace timeline as a ``timer``
    event the exporter renders as a B/E duration pair."""
    rec = Timer._registry.setdefault(
        name, {"calls": 0, "total_s": 0.0, "best_s": float("inf")}
    )
    rec["calls"] += 1
    rec["total_s"] += elapsed
    rec["best_s"] = min(rec["best_s"], elapsed)
    if _telemetry._MODE:
        _telemetry.on_timer(name, elapsed)


@functools.lru_cache(maxsize=None)
def _sync_probe(device):
    # A compiled no-op pinned to one device. Executable launches are ordered
    # per device, so blocking on its output waits for all previously enqueued
    # COMPUTE on that device — a device_put would ride the transfer stream and
    # can complete while compute is still running. (jax.effects_barrier is NOT
    # a substitute either: it waits on effect tokens, not async dispatch.)
    return jax.jit(lambda: jax.numpy.zeros(()), device=device)


def _sync_all_devices() -> None:
    try:
        for d in jax.local_devices():
            _sync_probe(d)().block_until_ready()
    except Exception:  # pragma: no cover - backend-dependent
        pass


def timed(fn: Optional[Callable] = None, *, name: Optional[str] = None, sync: bool = True):
    """Decorator recording each call of ``fn`` under ``name`` (default: its
    qualname) and blocking on any returned jax arrays so device time counts."""

    def wrap(f):
        label = name or f.__qualname__

        @functools.wraps(f)
        def inner(*args, **kwargs):
            with annotate(label), Timer(label, sync=False) as t:
                out = f(*args, **kwargs)
                if sync:
                    jax.block_until_ready(out)
            return out

        return inner

    return wrap(fn) if fn is not None else wrap


def report() -> Dict[str, Dict[str, float]]:
    """Aggregated timings: {name: {calls, total_s, mean_s, best_s}}."""
    out = {}
    for name, rec in Timer._registry.items():
        out[name] = {
            "calls": rec["calls"],
            "total_s": rec["total_s"],
            "mean_s": rec["total_s"] / rec["calls"],
            "best_s": rec["best_s"],
        }
    return out


def reset() -> None:
    """Clear the timer registry."""
    Timer._registry.clear()


# class-level aliases so `Timer.report()` / `Timer.reset()` read naturally
Timer.report = staticmethod(report)
Timer.reset = staticmethod(reset)


def device_memory_stats() -> Dict[str, Dict[str, int]]:
    """Live/peak bytes per device, where the backend exposes memory_stats()."""
    out: Dict[str, Dict[str, int]] = {}
    for d in jax.devices():
        try:
            stats = d.memory_stats()
        except Exception:  # pragma: no cover - backend-dependent
            stats = None
        if stats:
            out[str(d)] = {
                k: int(v)
                for k, v in stats.items()
                if isinstance(v, (int, float)) and "bytes" in k
            }
    return out


def host_memory_stats() -> Dict[str, int]:
    """This process's host memory picture: current/peak RSS and the
    machine's physical total — the memory surface that matters on forced-
    host CPU meshes where ``device_memory_stats`` is empty (the XLA CPU
    backend reports no memory_stats), and the denominator a fractional
    ``HEAT_TPU_MEMORY_BUDGET`` resolves against there. Best-effort: keys
    are present only where the platform exposes them."""
    out: Dict[str, int] = {}
    try:
        page = int(os.sysconf("SC_PAGE_SIZE"))
        with open("/proc/self/statm") as fh:
            rss_pages = int(fh.read().split()[1])
        out["rss_bytes"] = rss_pages * page
    except (OSError, ValueError, IndexError):  # pragma: no cover - non-Linux
        pass
    try:
        import resource

        # ru_maxrss is KiB on Linux
        out["peak_rss_bytes"] = int(
            resource.getrusage(resource.RUSAGE_SELF).ru_maxrss * 1024
        )
    except (ImportError, ValueError, OSError):  # pragma: no cover - non-POSIX
        pass
    try:
        out["total_bytes"] = int(os.sysconf("SC_PAGE_SIZE")) * int(
            os.sysconf("SC_PHYS_PAGES")
        )
    except (OSError, ValueError, AttributeError):  # pragma: no cover - non-POSIX
        pass
    return out

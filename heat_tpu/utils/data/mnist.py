"""MNIST dataset wrapper (reference: heat/utils/data/mnist.py:16-127).

The reference subclasses torchvision's MNIST and slices each rank's shard.
torchvision is optional here; when present, the data is ingested into the
sharded Dataset machinery.
"""

from __future__ import annotations

import numpy as np

from ...core import factories
from .datatools import Dataset

__all__ = ["MNISTDataset"]


class MNISTDataset(Dataset):
    """MNIST as a sharded in-memory Dataset (reference mnist.py:16-127).

    Parameters
    ----------
    root : str
        Download/cache directory.
    train : bool
    transform : callable, optional
    split : int or None
        Heat split axis for the image array (0 shards samples over devices).
    """

    def __init__(self, root: str, train: bool = True, transform=None, target_transform=None, split=0):
        from torchvision import datasets as tv_datasets  # noqa: deferred optional dep

        base = tv_datasets.MNIST(root, train=train, download=True)
        images = np.asarray(base.data.numpy(), dtype=np.float32) / 255.0
        labels = np.asarray(base.targets.numpy(), dtype=np.int32)
        img = factories.array(images, split=split)
        lbl = factories.array(labels, split=split)
        super().__init__([img, lbl], transform=transform)
        self.train = train

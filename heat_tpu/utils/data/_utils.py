"""Standalone data-preparation utilities (reference: heat/utils/data/_utils.py).

The reference ships two untested, unsupported helpers for converting ImageNet
TFRecord shards to HDF5 and producing DALI index files (reference
_utils.py:13-45, :47-260). The TPU-native analogs below keep the same names
and contract — byte-offset index files for TFRecord shards (pure stdlib; the
TFRecord wire format is ``{u64 length, u32 crc, payload, u32 crc}``), and a
merge of many record shards into the two big HDF5 files the
``PartialH5Dataset`` loader streams from — without requiring DALI or
TensorFlow.
"""

from __future__ import annotations

import os
import struct
from typing import List, Optional

import numpy as np

__all__ = ["dali_tfrecord2idx", "merge_files_imagenet_tfrecord"]


def _iter_tfrecord_offsets(path: str):
    """Yield (offset, total_record_length) for each record in a TFRecord file."""
    file_size = os.path.getsize(path)
    with open(path, "rb") as f:
        while True:
            start = f.tell()
            header = f.read(8)
            if len(header) < 8:
                return
            (proto_len,) = struct.unpack("<Q", header)
            end = start + 8 + 4 + proto_len + 4  # header, crc, payload, crc
            if end > file_size:
                raise ValueError(
                    f"{path}: corrupt or truncated TFRecord at offset {start} "
                    f"(record claims {proto_len} payload bytes, file has {file_size - start - 16})"
                )
            f.seek(end)
            yield start, end - start


def dali_tfrecord2idx(train_dir: str, train_idx_dir: str, val_dir: str, val_idx_dir: str) -> None:
    """Write ``<offset> <length>`` index lines for every TFRecord shard in the
    train/val directories (reference _utils.py:13-45). The index format is the
    one DALI's ``tfrecord2idx`` emits; producing it needs only the framing."""
    for src_dir, idx_dir in ((train_dir, train_idx_dir), (val_dir, val_idx_dir)):
        os.makedirs(idx_dir, exist_ok=True)
        for name in sorted(os.listdir(src_dir)):
            src = os.path.join(src_dir, name)
            if not os.path.isfile(src):
                continue
            with open(os.path.join(idx_dir, name), "w") as idx:
                for offset, length in _iter_tfrecord_offsets(src):
                    idx.write(f"{offset} {length}\n")


def merge_files_imagenet_tfrecord(folder_name: str, output_folder: Optional[str] = None) -> None:
    """Merge per-shard ``.npz`` record files (keys ``images``, ``labels``) into
    the two HDF5 files (``imagenet_merged.h5``, ``imagenet_merged_validation.h5``)
    that :class:`~heat_tpu.utils.data.partial_dataset.PartialH5Dataset` streams
    from (reference _utils.py:47-260 does the same from raw TFRecord protos).

    The reference decodes TF protobuf examples; without TensorFlow in the
    image, the supported interchange here is npz shards — any TFRecord set can
    be converted to npz shards offline with the index files from
    :func:`dali_tfrecord2idx`.
    """
    import h5py

    output_folder = output_folder or folder_name
    os.makedirs(output_folder, exist_ok=True)

    def shard_names(prefix: str) -> List[str]:
        return sorted(
            os.path.join(folder_name, f)
            for f in os.listdir(folder_name)
            if f.startswith(prefix) and f.endswith(".npz")
        )

    for prefix, out_name in (
        ("train", "imagenet_merged.h5"),
        ("val", "imagenet_merged_validation.h5"),
    ):
        shards = shard_names(prefix)
        if not shards:
            continue
        out_path = os.path.join(output_folder, out_name)
        with h5py.File(out_path, "w") as out:
            img_ds = lbl_ds = None
            for shard in shards:
                with np.load(shard) as data:
                    images, labels = data["images"], data["labels"]
                if img_ds is None:
                    img_ds = out.create_dataset(
                        "images", shape=(0,) + images.shape[1:], maxshape=(None,) + images.shape[1:],
                        dtype=images.dtype, chunks=True,
                    )
                    lbl_ds = out.create_dataset(
                        "metadata", shape=(0,) + labels.shape[1:], maxshape=(None,) + labels.shape[1:],
                        dtype=labels.dtype, chunks=True,
                    )
                n = img_ds.shape[0]
                img_ds.resize(n + images.shape[0], axis=0)
                lbl_ds.resize(n + labels.shape[0], axis=0)
                img_ds[n:] = images
                lbl_ds[n:] = labels

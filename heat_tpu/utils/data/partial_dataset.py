"""Out-of-core HDF5 streaming dataset (reference: heat/utils/data/partial_dataset.py).

The reference's ``PartialH5Dataset`` (partial_dataset.py:32-230) keeps only a
window of a large HDF5 file in memory, with background threads loading and
converting the next window while the current one trains. Here the same
double-buffering uses a single loader thread (h5py releases the GIL for I/O)
and JAX's async dispatch hides host→device copies.
"""

from __future__ import annotations

import queue
import threading
from typing import Iterator, List, Optional

import numpy as np

from ...core.dndarray import DNDarray

__all__ = ["PartialH5Dataset", "PartialH5DataLoaderIter", "queue_thread"]


def queue_thread(q: "queue.Queue") -> threading.Thread:
    """Spawn a daemon worker draining work items from ``q`` until a ``None``
    sentinel (the reference's background load/convert thread pool primitive,
    reference partial_dataset.py:20-31). An item is a bare callable or a
    ``(fn, *args)`` tuple. ``task_done`` is guaranteed per item so ``q.join()``
    cannot deadlock on a raising work function."""

    def worker():
        while True:
            item = q.get()
            try:
                if item is None:
                    return
                if callable(item):
                    item()
                else:
                    fn, *args = item
                    # allow both (fn, (a, b)) and (fn, a, b)
                    if len(args) == 1 and isinstance(args[0], tuple):
                        args = args[0]
                    fn(*args)
            except Exception:  # noqa: BLE001 - background worker must survive
                import traceback

                traceback.print_exc()
            finally:
                q.task_done()

    t = threading.Thread(target=worker, daemon=True)
    t.start()
    return t


class PartialH5Dataset:
    """Windowed loader over one or more datasets of an HDF5 file
    (reference partial_dataset.py:32-142).

    Parameters
    ----------
    file : str
        HDF5 path.
    comm : unused, kept for parity.
    dataset_names : list of str
        Names of the HDF5 datasets to stream (first axes aligned).
    initial_load : int
        Window size (number of rows held in memory).
    load_length : int
        Rows loaded per background refill.
    transforms : callable or list, optional
    use_gpu : bool
        Parity flag; placement is mesh-driven.
    """

    def __init__(
        self,
        file: str,
        comm=None,
        dataset_names="data",
        transforms=None,
        use_gpu: bool = True,
        validate_set: bool = False,
        initial_load: int = 7000,
        load_length: int = 1000,
    ):
        import h5py

        self.file = file
        self.dataset_names = (
            [dataset_names] if isinstance(dataset_names, str) else list(dataset_names)
        )
        self.transforms = transforms if isinstance(transforms, (list, tuple)) else (
            [transforms] if transforms is not None else None
        )
        self.initial_load = initial_load
        self.load_length = load_length
        with h5py.File(file, "r") as handle:
            self.total_size = handle[self.dataset_names[0]].shape[0]
        self.length = self.total_size

    def __len__(self) -> int:
        return self.length

    def __iter__(self):
        raise TypeError("iterate via PartialH5DataLoaderIter")


class PartialH5DataLoaderIter:
    """Batched iterator with a background prefetch thread
    (reference partial_dataset.py:143-230)."""

    def __init__(self, dataset: PartialH5Dataset, batch_size: int, shuffle: bool = True, seed: int = 0):
        self.dataset = dataset
        self.batch_size = batch_size
        self.shuffle = shuffle
        self.seed = seed

    def __len__(self) -> int:
        return len(self.dataset) // self.batch_size

    def __iter__(self) -> Iterator[List[np.ndarray]]:
        import h5py

        ds = self.dataset
        window = ds.initial_load
        q: "queue.Queue" = queue.Queue(maxsize=2)

        def loader():
            with h5py.File(ds.file, "r") as handle:
                handles = [handle[name] for name in ds.dataset_names]
                for start in range(0, ds.total_size, window):
                    stop = min(start + window, ds.total_size)
                    q.put([np.asarray(h[start:stop]) for h in handles])
            q.put(None)

        t = threading.Thread(target=loader, daemon=True)
        t.start()

        rng = np.random.default_rng(self.seed)
        while True:
            chunk = q.get()
            if chunk is None:
                break
            n = chunk[0].shape[0]
            order = rng.permutation(n) if self.shuffle else np.arange(n)
            for bstart in range(0, n - self.batch_size + 1, self.batch_size):
                idx = order[bstart : bstart + self.batch_size]
                batch = [c[idx] for c in chunk]
                if ds.transforms is not None:
                    batch = [
                        (tf(b) if tf is not None else b)
                        for tf, b in zip(ds.transforms, batch)
                    ]
                yield batch if len(batch) > 1 else batch[0]
        t.join()

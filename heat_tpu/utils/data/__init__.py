"""Data-loading utilities (reference: heat/utils/data/__init__.py)."""

from . import datatools, matrixgallery, partial_dataset
from .datatools import *
from .matrixgallery import *
from .partial_dataset import *

try:  # torchvision-backed MNIST dataset is optional (reference mnist.py)
    from .mnist import MNISTDataset
except Exception:  # pragma: no cover
    MNISTDataset = None

"""Dataset and DataLoader (reference: heat/utils/data/datatools.py).

The reference keeps each rank's shard in memory and reshuffles globally
between epochs by Alltoall-ing half-shards (datatools.py:246-343). Here the
dataset holds the global (sharded) arrays; the inter-epoch shuffle is one
global permutation gather whose collectives XLA derives — same effect, one
line. Batches are yielded as device arrays ready for a jitted train step.
"""

from __future__ import annotations

from typing import Iterator, List, Optional, Sequence, Tuple, Union

import jax
import jax.numpy as jnp
import numpy as np

from ...core import random as ht_random
from ...core.dndarray import DNDarray

__all__ = ["DataLoader", "Dataset", "dataset_shuffle", "dataset_ishuffle", "dataset_irecv"]


class Dataset:
    """In-memory dataset over one or more aligned arrays (reference
    datatools.py:30-148).

    Parameters
    ----------
    array : DNDarray or sequence of DNDarray
        Data (and optionally labels, etc.), first axes aligned.
    transform : callable, optional
        Applied per retrieved item.
    ishuffle : bool
        Kept for API parity; shuffling happens in the DataLoader.
    """

    def __init__(self, array, transform=None, ishuffle: bool = False, test_set=None):
        if isinstance(array, DNDarray):
            self.arrays = [array]
        else:
            self.arrays = list(array)
        n = self.arrays[0].shape[0]
        for a in self.arrays[1:]:
            if a.shape[0] != n:
                raise ValueError("all arrays must have the same first dimension")
        self.transform = transform
        self.ishuffle = ishuffle
        self.test_set = test_set

    def __len__(self) -> int:
        return self.arrays[0].shape[0]

    def __getitem__(self, index):
        items = [a.larray[index] for a in self.arrays]
        if self.transform is not None:
            items[0] = self.transform(items[0])
        return items[0] if len(items) == 1 else tuple(items)

    def shuffle(self):
        """Global random permutation of all arrays (reference datatools.py:246-297)."""
        n = len(self)
        perm = ht_random.randperm(n).larray
        for a in self.arrays:
            a.larray = jnp.take(a.larray, perm, axis=0)

    def ishuffle_(self):
        """Non-blocking shuffle in the reference (:298-343); dispatch is async
        under JAX anyway, so this is the same global permutation."""
        self.shuffle()


class DataLoader:
    """Iterator of device-ready batches (reference datatools.py:149-245).

    Parameters
    ----------
    dataset : Dataset or DNDarray
    batch_size : int
    shuffle : bool
        Reshuffle globally at the start of every epoch.
    drop_last : bool
        Drop the trailing ragged batch (True keeps every batch jit-shape-stable).
    """

    def __init__(
        self,
        dataset=None,
        batch_size: int = 1,
        shuffle: bool = False,
        drop_last: bool = True,
        lcl_dataset=None,
    ):
        if dataset is None and lcl_dataset is not None:
            dataset = lcl_dataset
        if isinstance(dataset, DNDarray):
            dataset = Dataset(dataset)
        if not isinstance(dataset, Dataset):
            raise TypeError(f"dataset must be a Dataset or DNDarray, got {type(dataset)}")
        if batch_size < 1:
            raise ValueError(f"batch_size must be positive, got {batch_size}")
        self.dataset = dataset
        self.batch_size = batch_size
        self.shuffle = shuffle
        self.drop_last = drop_last

    def __len__(self) -> int:
        n = len(self.dataset)
        if self.drop_last:
            return n // self.batch_size
        return -(-n // self.batch_size)

    def __iter__(self) -> Iterator:
        if self.shuffle:
            self.dataset.shuffle()
        n = len(self.dataset)
        bs = self.batch_size
        stop = (n // bs) * bs if self.drop_last else n
        for start in range(0, stop, bs):
            yield self.dataset[start : min(start + bs, n)]


def dataset_shuffle(dataset: Dataset, attrs=None) -> None:
    """Module-level shuffle hook (reference datatools.py:246-297)."""
    dataset.shuffle()


def dataset_ishuffle(dataset: Dataset, attrs=None) -> None:
    """Non-blocking shuffle hook (reference datatools.py:298-343)."""
    dataset.ishuffle_()


def dataset_irecv(dataset: Dataset, attrs=None) -> None:
    """Completion hook for the non-blocking shuffle: the reference waits on
    the Irecv halves and splices them into the local shard
    (reference datatools.py:344-392). JAX dispatch is already asynchronous —
    the permuted arrays materialize when first consumed — so completing the
    exchange is a device-side sync of the shuffled arrays."""
    for a in dataset.arrays:
        jax.block_until_ready(a.larray)

"""Test-matrix generators (reference: heat/utils/data/matrixgallery.py)."""

from __future__ import annotations

from typing import Optional, Tuple

import jax.numpy as jnp
import numpy as np

from ...core import factories, types
from ...core import random as ht_random
from ...core.communication import sanitize_comm
from ...core.dndarray import DNDarray

__all__ = ["hermitian", "parter", "random_known_rank"]


def parter(n: int, split: Optional[int] = None, device=None, comm=None, dtype=types.float32) -> DNDarray:
    """Parter matrix A[i,j] = 1 / (i - j + 0.5) — a Cauchy matrix with
    singular values clustered at pi (reference matrixgallery.py:14-56)."""
    i = jnp.arange(n, dtype=types.canonical_heat_type(dtype).jax_type())
    a = 1.0 / (i[:, None] - i[None, :] + 0.5)
    return factories.array(a, split=split, device=device, comm=comm, dtype=dtype)


def hermitian(
    n: int, split: Optional[int] = None, device=None, comm=None, dtype=types.complex64, positive_definite: bool = False
) -> DNDarray:
    """Random Hermitian (or symmetric, for real dtypes) matrix (reference
    matrixgallery.py:57-120)."""
    cplx = types.heat_type_is_complexfloating(dtype)
    real = ht_random.randn(n, n, split=split, device=device, comm=comm)
    if cplx:
        imag = ht_random.randn(n, n, split=split, device=device, comm=comm)
        a = real.larray + 1j * imag.larray
    else:
        a = real.larray
    if positive_definite:
        h = a @ jnp.conj(a.T) + n * jnp.eye(n, dtype=a.dtype)
    else:
        h = 0.5 * (a + jnp.conj(a.T))
    return factories.array(h, split=split, device=device, comm=comm, dtype=dtype)


def random_known_rank(
    m: int, n: int, rank: int, split: Optional[int] = None, device=None, comm=None, dtype=types.float32
) -> Tuple[DNDarray, Tuple[DNDarray, DNDarray]]:
    """Random matrix of known rank, returned with its factors (reference
    matrixgallery.py:121-170)."""
    if rank > min(m, n):
        raise ValueError(f"rank must be <= min(m, n) = {min(m, n)}, got {rank}")
    u = ht_random.randn(m, rank, split=split, device=device, comm=comm)
    v = ht_random.randn(n, rank, device=device, comm=comm)
    a = u.larray @ v.larray.T
    return (
        factories.array(a, split=split, device=device, comm=comm, dtype=dtype),
        (u, v),
    )

"""Utility subpackages (reference: heat/utils/__init__.py)."""

from . import data

"""Utility subpackages (reference: heat/utils/__init__.py, plus the
TPU-build-new checkpoint and profiling subsystems called for by SURVEY.md §5)."""

from . import checkpoint, data, health, profiling

"""Hierarchical data-parallel optimizers.

TPU-native re-design of reference heat/optim/dp_optimizer.py. DASO's topology
in the reference is two-level: torch-DDP over NCCL inside a node, plus a
skip-scheduled MPI group-Iallreduce of the flattened bf16 parameter vector
between nodes (dp_optimizer.py:181-195 groups, :432-475 local step, :592-650
global send, :501-589 stale-weighted merge, :60-66/:336-431 warmup/cycling/
cooldown phases). The TPU analog is literal: a 2-axis device mesh
``('dcn', 'ici')`` where the fast axis is intra-slice ICI and the slow axis
inter-slice DCN. Every step syncs gradients over 'ici' only (params carry a
leading dcn-group dimension, sharded over 'dcn', so groups evolve
independently); every ``global_skips`` batches the groups are merged over
'dcn' with the reference's stale weighting; global traffic rides one psum in
bfloat16 (the reference's custom bf16 MPI op, dp_optimizer.py:21-43, is a
dtype cast here).
"""

from __future__ import annotations

import warnings
from typing import Any, Callable, Optional

import jax
import jax.numpy as jnp
import numpy as np
import optax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from ..core import numlens
from ..core.communication import MeshCommunication, sanitize_comm
from .utils import DetectMetricPlateau

__all__ = ["DASO", "DataParallelOptimizer"]


class DataParallelOptimizer:
    """Wrapper binding an optax transformation to data-parallel training
    (reference dp_optimizer.py:836-877 wraps a torch optimizer and gates its
    step; optax transformations are already functional, so this holds the
    state and exposes the same surface)."""

    def __init__(self, optimizer, blocking: bool = False):
        if not isinstance(blocking, bool):
            raise TypeError(f"blocking parameter must be a bool, currently {type(blocking)}")
        self.torch_optimizer = optimizer  # parity name
        self.optimizer = optimizer
        self.blocking = blocking
        self.opt_state = None
        self.update_next = True

    def init(self, params):
        self.opt_state = self.optimizer.init(params)
        return self.opt_state

    def step(self, grads, params):
        updates, self.opt_state = self.optimizer.update(grads, self.opt_state, params)
        return optax.apply_updates(params, updates)

    def zero_grad(self):
        """No-op: functional gradients have no buffers to clear."""


def _cross_entropy(logits, labels):
    if labels.ndim == logits.ndim:
        return optax.softmax_cross_entropy(logits, labels).mean()
    return optax.softmax_cross_entropy_with_integer_labels(logits, labels).mean()


class DASO:
    """Distributed Asynchronous and Selective Optimization (reference
    dp_optimizer.py:46-180 constructor contract).

    Parameters
    ----------
    local_optimizer : optax.GradientTransformation
        Per-group optimizer (the reference takes a torch optimizer).
    total_epochs : int
    comm : MeshCommunication, optional
        Devices to organize as the 2-axis (dcn × ici) topology.
    nodes : int, optional
        Number of simulated DCN groups; defaults to 2 when the device count
        allows it (the reference reads this from the MPI host topology).
    warmup_epochs, cooldown_epochs : int
        Full-synchronization phases at both ends (reference :60-66).
    max_global_skips : int
        Ceiling on the skip schedule.
    stability_level : float
        Plateau threshold driving the schedule (reference :336-431).
    use_mpi_groups : bool
        Parity flag; group formation is mesh reshaping here.
    downcast_type : dtype
        Wire format of the DCN merge (default bfloat16, reference :21-43).
    """

    def __init__(
        self,
        local_optimizer,
        total_epochs: int,
        comm: Optional[MeshCommunication] = None,
        nodes: Optional[int] = None,
        warmup_epochs: int = 4,
        cooldown_epochs: int = 4,
        scheduler=None,
        stability_level: float = 0.05,
        max_global_skips: int = 8,
        sending_chunk_size: int = 10_000_000,
        downcast_type=jnp.bfloat16,
        use_mpi_groups: bool = True,
        skip_batches: Optional[int] = None,
        local_skip_factor: int = 4,
        verbose: bool = False,
    ):
        if not isinstance(total_epochs, int):
            raise TypeError(f"total_epochs must be an int, currently {type(total_epochs)}")
        if warmup_epochs < 0 or cooldown_epochs < 0:
            raise ValueError("warmup/cooldown epochs must be non-negative")

        self.comm = sanitize_comm(comm)
        n_dev = self.comm.size
        if nodes is None:
            nodes = 2 if n_dev % 2 == 0 and n_dev > 1 else 1
        if n_dev % nodes != 0:
            raise ValueError(f"device count {n_dev} not divisible into {nodes} DCN groups")
        self.nodes = nodes
        self.ici_size = n_dev // nodes
        devices = np.asarray(self.comm.devices).reshape(nodes, self.ici_size)
        self.mesh = Mesh(devices, ("dcn", "ici"))

        self.local_optimizer = local_optimizer
        self.total_epochs = total_epochs
        self.warmup_epochs = warmup_epochs
        self.cooldown_epochs = cooldown_epochs
        self.scheduler = scheduler
        self.max_gs = max_global_skips
        self.verbose = verbose
        self.downcast_type = downcast_type

        # skip schedule state (reference dp_optimizer.py:60-66).
        # local_skip drives the ICI sync cadence (reference :432-475): while
        # local-skipping, devices inside a DCN group step independently (no
        # gradient allreduce); every local_skip-th batch re-averages params
        # over ICI and syncs gradients again.
        self.global_skip = 0
        self.local_skip = 0
        self.local_skip_factor = int(local_skip_factor)
        self.batches_to_wait = 0
        self.epoch = 0
        self.current_batch = 0
        self._send_mod = skip_batches
        self._solo_steps = 0  # observability: batches stepped without ICI sync

        self.stability = DetectMetricPlateau(
            patience=2, threshold=stability_level, threshold_mode="rel"
        )
        self.split = None  # parity attribute

        self.module = None
        self.params = None  # leading dcn-group axis, sharded over 'dcn'
        self.opt_state = None
        self.loss_fn = _cross_entropy
        self._local_step = None
        self._global_merge = None
        self._stateful = False
        self.state = None

    # ------------------------------------------------------------------
    # wiring
    # ------------------------------------------------------------------
    def add_model(self, module, rng_seed: int, sample_input) -> "DASO":
        """Attach the network (the reference receives a DataParallelMultiGPU
        wrapper, dp_optimizer.py:197-230)."""
        self.module = module
        sample = jnp.asarray(sample_input)
        variables = module.init(jax.random.PRNGKey(rng_seed), sample)
        self._stateful = "batch_stats" in variables
        if self._stateful:
            params = variables["params"]
            self.state = jax.tree.map(
                lambda a: jnp.broadcast_to(a, (self.nodes * self.ici_size,) + a.shape),
                {k: v for k, v in variables.items() if k != "params"},
            )
        else:
            params = variables
        # one replica per DEVICE (leading axis over the flattened dcn x ici
        # mesh): replicas inside a group may diverge while local-skipping —
        # the reference's local_skip semantics (dp_optimizer.py:432-475)
        n_dev = self.nodes * self.ici_size
        self.params = jax.tree.map(
            lambda a: jnp.broadcast_to(a, (n_dev,) + a.shape), params
        )
        single_opt_state = self.local_optimizer.init(params)
        self.opt_state = jax.tree.map(
            lambda a: jnp.broadcast_to(jnp.asarray(a), (n_dev,) + jnp.shape(a)),
            single_opt_state,
        )
        self._build()
        self._place()
        return self

    def _spec_grouped(self):
        return P(("dcn", "ici"))

    def _place(self):
        grouped = NamedSharding(self.mesh, P(("dcn", "ici")))
        self.params = jax.tree.map(lambda a: jax.device_put(a, grouped), self.params)
        self.opt_state = jax.tree.map(
            lambda a: jax.device_put(jnp.asarray(a), grouped) if hasattr(a, "shape") else a,
            self.opt_state,
        )
        if self.state is not None:
            self.state = jax.tree.map(lambda a: jax.device_put(a, grouped), self.state)

    def _build(self):
        mesh = self.mesh
        opt = self.local_optimizer
        module = self.module
        loss_fn = self.loss_fn
        stateful = self._stateful

        group_spec = P(("dcn", "ici"))
        batch_spec = P(("dcn", "ici"))

        def make_local_step(sync_ici: bool):
            """One batch. ``sync_ici=True`` is the reference's synced batch:
            params are re-averaged over ICI (a no-op when replicas agree,
            the re-convergence sync after a local-skip window) and gradients
            ride the torch-DDP-style ICI allreduce. ``sync_ici=False`` is a
            local-skip batch (reference dp_optimizer.py:432-475): every
            device steps its own replica with no ICI traffic at all."""

            def local_step(params, state, opt_state, x, y):
                def kernel(p, s, o, xb, yb):
                    # inside shard_map: p/s/o are THIS device's replica
                    p = jax.tree.map(lambda a: a[0], p)
                    o = jax.tree.map(lambda a: a[0], o)
                    if sync_ici:
                        p = jax.lax.pmean(p, "ici")

                    def loss_of(pp):
                        if stateful:
                            s0 = jax.tree.map(lambda a: a[0], s)
                            out, new_s = module.apply(
                                {"params": pp, **s0}, xb, train=True, mutable=["batch_stats"]
                            )
                            return loss_fn(out, yb), new_s
                        return loss_fn(module.apply(pp, xb), yb), None

                    (loss, new_s), grads = jax.value_and_grad(loss_of, has_aux=True)(p)
                    expand = lambda t: jax.tree.map(lambda a: a[None], t)
                    if sync_ici:
                        # ICI gradient sync (the torch-DDP allreduce)
                        grads = jax.lax.pmean(grads, "ici")
                        loss_out = jax.lax.pmean(loss, ("dcn", "ici"))
                    else:
                        # solo batch: ZERO collectives — the per-device loss
                        # ships out sharded and is averaged on the host
                        loss_out = loss[None]
                    updates, o = opt.update(grads, o, p)
                    p = optax.apply_updates(p, updates)
                    if stateful:
                        new_s = expand(
                            jax.lax.pmean(new_s, "ici") if sync_ici else new_s
                        )
                    else:
                        new_s = s
                    return expand(p), new_s, expand(o), loss_out

                loss_spec = P() if sync_ici else P(("dcn", "ici"))
                return jax.shard_map(
                    kernel,
                    mesh=mesh,
                    in_specs=(group_spec, group_spec, group_spec, batch_spec, batch_spec),
                    out_specs=(group_spec, group_spec, group_spec, loss_spec),
                    check_vma=False,
                )(params, state, opt_state, x, y)

            return local_step

        def global_merge(params, waits):
            """Stale-weighted DCN merge (reference dp_optimizer.py:501-589):
            the fresh global average is blended with the local (stale-ahead)
            parameters as (global + waits·local) / (waits + 1), travelling in
            the downcast wire dtype."""

            def kernel(p):
                local = jax.tree.map(lambda a: a[0], p)
                wire = jax.tree.map(lambda a: a.astype(self.downcast_type), local)
                gmean = jax.lax.pmean(wire, ("dcn", "ici"))
                merged = jax.tree.map(
                    lambda g, l: ((g.astype(l.dtype) + waits * l) / (waits + 1.0)),
                    gmean,
                    local,
                )
                return jax.tree.map(lambda a: a[None], merged)

            return jax.shard_map(
                kernel,
                mesh=mesh,
                in_specs=(group_spec,),
                out_specs=group_spec,
                check_vma=False,
            )(params)

        self._local_step = jax.jit(make_local_step(sync_ici=True))
        self._local_step_solo = jax.jit(make_local_step(sync_ici=False))
        self._global_merge = jax.jit(global_merge)

    # ------------------------------------------------------------------
    # training surface
    # ------------------------------------------------------------------
    def step(self, x, y) -> float:
        """One DASO batch step (reference dp_optimizer.py:730-815): local/ICI
        step always; DCN merge when the skip schedule says so."""
        if self.params is None:
            raise RuntimeError("add_model must be called before step")
        batch_sh = NamedSharding(self.mesh, P(("dcn", "ici")))
        xj, yj = jnp.asarray(x), jnp.asarray(y)
        n_dev = self.nodes * self.ici_size
        rem = xj.shape[0] % n_dev
        if rem:
            # the reference's DataLoader guarantees equal local batches by
            # construction (reference utils/data/datatools.py chunking); the
            # shard_map step needs the same, so drop the remainder like a
            # drop_last loader would
            if xj.shape[0] < n_dev:
                raise ValueError(
                    f"batch of {xj.shape[0]} is smaller than the {n_dev}-device mesh"
                )
            if not getattr(self, "_warned_remainder", False):
                warnings.warn(
                    f"batch size {xj.shape[0]} is not divisible by the {n_dev}-device "
                    f"mesh; dropping the last {rem} sample(s) each step"
                )
                self._warned_remainder = True
            xj, yj = xj[: xj.shape[0] - rem], yj[: yj.shape[0] - rem]
        xb = jax.device_put(xj, batch_sh)
        yb = jax.device_put(yj, batch_sh)
        state = self.state if self.state is not None else {}
        # local-skip cadence (reference dp_optimizer.py:432-475): between
        # ICI syncs each device steps its own replica with zero collective
        # traffic; every local_skip-th batch re-averages params over ICI and
        # syncs gradients again
        ls = self._effective_local_skip()
        solo = ls > 1 and (self.current_batch % ls) != 0
        step_fn = self._local_step_solo if solo else self._local_step
        if solo:
            self._solo_steps += 1
        self.params, new_state, self.opt_state, loss = step_fn(
            self.params, state, self.opt_state, xb, yb
        )
        if self._stateful:
            self.state = new_state

        self.current_batch += 1
        gs = self._effective_global_skip()
        if gs == 0 or self.current_batch % (gs + 1) == 0:
            waits = float(min(self.batches_to_wait, gs))
            pre_merge = self.params if numlens.active() else None
            self.params = self._global_merge(self.params, jnp.float32(waits))
            if pre_merge is not None:
                # numerics lens (HEAT_TPU_NUMLENS): per-merge update-ratio /
                # loss streams + plateau/overflow detection — one module-attr
                # read when disarmed
                numlens.note_training(
                    "daso.merge", loss=jnp.mean(loss),
                    params=self.params, prev_params=pre_merge,
                )
        # solo batches return per-device losses (no in-program collective);
        # average on the host for a uniform scalar contract
        return float(jnp.mean(loss))

    def _effective_global_skip(self) -> int:
        if self.epoch < self.warmup_epochs:
            return 0
        if self.epoch >= self.total_epochs - self.cooldown_epochs:
            return 0
        return self.global_skip

    def _effective_local_skip(self) -> int:
        """ICI sync cadence: always synced during warmup/cooldown, the
        scheduled ``local_skip`` during the cycling phase."""
        if self.epoch < self.warmup_epochs:
            return 0
        if self.epoch >= self.total_epochs - self.cooldown_epochs:
            return 0
        return self.local_skip

    def epoch_loss_logic(self, loss, loss_globally_averaged: bool = False) -> None:
        """End-of-epoch schedule update (reference dp_optimizer.py:336-431):
        entering the cycling phase starts at max skips; a loss plateau halves
        the skips; full stability resets upward."""
        loss_val = float(loss)
        self.epoch += 1
        self.current_batch = 0
        if self.epoch == self.warmup_epochs:
            self.global_skip = 4
            self.local_skip = max(1, 4 // self.local_skip_factor)
            self.batches_to_wait = 1
            self._print0(f"warmup done; global_skips={self.global_skip}")
            return
        if self.epoch < self.warmup_epochs or self.epoch > self.total_epochs - self.cooldown_epochs:
            return
        stable = self.stability.test_if_improving(loss_val)
        if stable and self.global_skip > 1:
            # loss stopped improving -> tighten synchronization (local skips
            # halve together with global skips, reference dp_optimizer.py:377-409)
            self.global_skip //= 2
            self.local_skip = max(1, self.local_skip // 2)
            self.batches_to_wait = max(self.batches_to_wait // 2, 1)
            self._print0(f"loss plateau; global_skips -> {self.global_skip}")
        elif self.global_skip == 1 and stable:
            self.global_skip = min(self.max_gs, 4)
            self.local_skip = max(1, self.global_skip // self.local_skip_factor)
            self.batches_to_wait = 1
            self.stability.reset()
            self._print0(f"resetting skips upward -> {self.global_skip}")

    def _print0(self, msg: str) -> None:
        if self.verbose and self.comm.rank == 0:
            print(f"[DASO] {msg}")

    # ------------------------------------------------------------------
    def forward(self, x):
        """Evaluate group 0's model replica."""
        p0 = jax.tree.map(lambda a: a[0], self.params)
        if self._stateful:
            s0 = jax.tree.map(lambda a: a[0], self.state)
            return self.module.apply({"params": p0, **s0}, jnp.asarray(x))
        return self.module.apply(p0, jnp.asarray(x))

    __call__ = forward

    def zero_grad(self) -> None:
        """No-op under functional gradients (reference dp_optimizer.py:816-833)."""

    # ------------------------------------------------------------------
    # checkpoint / resume (the reference exposes DetectMetricPlateau
    # get_state/set_state but nothing serializes them, SURVEY.md §5; here the
    # full trainer — params, optimizer, skip schedule, plateau controller —
    # round-trips through heat_tpu.utils.checkpoint)
    # ------------------------------------------------------------------
    def state_dict(self):
        """Full resumable state. Restoring requires the same mesh layout
        (params carry a leading per-device replica axis)."""
        return {
            "params": self.params,
            "state": self.state if self.state is not None else {},
            "opt_state": self.opt_state,
            "schedule": {
                "epoch": self.epoch,
                "current_batch": self.current_batch,
                "global_skip": self.global_skip,
                "local_skip": self.local_skip,
                "batches_to_wait": self.batches_to_wait,
            },
            "stability": self.stability.get_state(),
        }

    def load_state_dict(self, sd) -> "DASO":
        self.params = sd["params"]
        if self._stateful:
            self.state = sd["state"]
        self.opt_state = sd["opt_state"]
        sched = sd["schedule"]
        self.epoch = int(sched["epoch"])
        self.current_batch = int(sched["current_batch"])
        self.global_skip = int(sched["global_skip"])
        self.local_skip = int(sched["local_skip"])
        self.batches_to_wait = int(sched["batches_to_wait"])
        self.stability.set_state(sd["stability"])
        self._place()  # re-establish the dcn shardings on this mesh
        return self

    # ------------------------------------------------------------------
    # elastic surface (core/elastic.py): mesh-shape-independent state and
    # world rebinding, so a preempted job can restore onto a SHRUNK mesh
    # ------------------------------------------------------------------
    def elastic_state_dict(self):
        """Mesh-shape-independent resumable state.

        :meth:`state_dict` params carry a leading per-device replica axis —
        restorable only onto the same device count. Here that axis is merged
        out (float leaves averaged, int/bool leaves take replica 0 — an optax
        step counter must not float-promote), which is exact whenever the
        replicas agree (warmup/cooldown, or right after a global merge) and
        the DASO stale-averaging approximation otherwise."""

        def merge(a):
            a = jnp.asarray(a)
            if jnp.issubdtype(a.dtype, jnp.integer) or jnp.issubdtype(a.dtype, jnp.bool_):
                return a[0]
            return jnp.mean(a, axis=0)

        sd = self.state_dict()
        return {
            "params": jax.tree.map(merge, sd["params"]),
            "state": jax.tree.map(merge, sd["state"]),
            "opt_state": jax.tree.map(merge, sd["opt_state"]),
            "schedule": sd["schedule"],
            "stability": sd["stability"],
        }

    def load_elastic_state_dict(self, sd) -> "DASO":
        """Restore :meth:`elastic_state_dict` state onto the CURRENT mesh:
        the merged replica broadcasts to this world's device count."""
        n_dev = self.nodes * self.ici_size
        bcast = lambda t: jax.tree.map(
            lambda a: jnp.broadcast_to(jnp.asarray(a), (n_dev,) + jnp.shape(a)), t
        )
        self.params = bcast(sd["params"])
        if self._stateful:
            self.state = bcast(sd["state"])
        self.opt_state = bcast(sd["opt_state"])
        sched = sd["schedule"]
        self.epoch = int(sched["epoch"])
        self.current_batch = int(sched["current_batch"])
        self.global_skip = int(sched["global_skip"])
        self.local_skip = int(sched["local_skip"])
        self.batches_to_wait = int(sched["batches_to_wait"])
        self.stability.set_state(sd["stability"])
        self._place()
        return self

    def rebind(self, comm: Optional[MeshCommunication] = None) -> "DASO":
        """Re-target this trainer onto a (possibly shrunk) world.

        The elastic reform step: carries the live state across via
        :meth:`elastic_state_dict`, rebuilds the 2-axis mesh and the jitted
        step/merge programs over the new device set (an old program would
        dispatch against lost devices), and re-places the state. The DCN
        group count shrinks to a divisor of the new device count when the
        old one no longer divides it."""
        sd = self.elastic_state_dict() if self.params is not None else None
        self.comm = sanitize_comm(comm)
        n_dev = self.comm.size
        if self.nodes > n_dev or n_dev % self.nodes != 0:
            self.nodes = 2 if n_dev % 2 == 0 and n_dev > 1 else 1
        self.ici_size = n_dev // self.nodes
        devices = np.asarray(self.comm.devices).reshape(self.nodes, self.ici_size)
        self.mesh = Mesh(devices, ("dcn", "ici"))
        if self.module is not None:
            self._build()
        if sd is not None:
            self.load_elastic_state_dict(sd)
        return self

    def save(self, directory: str, step: int = 0, keep: int = 3) -> str:
        """Write a manifest-based checkpoint ``directory/ckpt_{step}.manifest.json``
        (+ per-leaf payload files; the manifest rename is the commit point —
        a kill mid-save leaves the previous checkpoint restorable, never a
        torn hybrid). Keeps the newest ``keep``."""
        from ..utils.checkpoint import save_checkpoint

        return save_checkpoint(directory, self.state_dict(), step=step, keep=keep)

    def restore(self, directory: str, step=None, strict: bool = False) -> "DASO":
        """Resume from a checkpoint written by :meth:`save`.

        ``step=None`` restores the newest checkpoint that *verifies*
        (checksum-checked; a torn/corrupt newest is skipped with a warning —
        ``strict=True`` raises instead). An explicit ``step`` that does not
        exist on disk raises ``FileNotFoundError`` listing the available
        steps rather than silently loading the newest."""
        from ..utils.checkpoint import load_checkpoint

        return self.load_state_dict(
            load_checkpoint(directory, self.state_dict(), step=step, strict=strict)
        )

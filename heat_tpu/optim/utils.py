"""Optimizer utilities (reference: heat/optim/utils.py).

``DetectMetricPlateau`` is a faithful re-implementation of the reference's
loss-stability controller (utils.py:14-206): it watches a metric over a
patience window and reports when it has stopped improving.
"""

from __future__ import annotations

from typing import Dict, Optional

__all__ = ["DetectMetricPlateau"]


class DetectMetricPlateau:
    """Detect if a metric plateaus (reference optim/utils.py:14-71).

    Parameters
    ----------
    mode : 'min' or 'max'
        Whether lower or higher metric values are better.
    patience : int
        Epochs with no improvement before declaring a plateau.
    threshold : float
        Minimum relative/absolute change counting as improvement.
    threshold_mode : 'rel' or 'abs'
    """

    def __init__(
        self,
        mode: str = "min",
        patience: int = 10,
        threshold: float = 1e-4,
        threshold_mode: str = "rel",
    ):
        if mode not in ("min", "max"):
            raise ValueError(f"mode {mode} is unknown!")
        if threshold_mode not in ("rel", "abs"):
            raise ValueError(f"threshold mode {threshold_mode} is unknown!")
        self.mode = mode
        self.patience = patience
        self.threshold = threshold
        self.threshold_mode = threshold_mode
        self.num_bad_epochs: int = 0
        self.mode_worse: Optional[float] = float("inf") if mode == "min" else -float("inf")
        self.best = self.mode_worse
        self.last_epoch = 0

    def get_state(self) -> Dict:
        """Serializable state dict (reference utils.py:72-89)."""
        return {
            "mode": self.mode,
            "patience": self.patience,
            "threshold": self.threshold,
            "threshold_mode": self.threshold_mode,
            "num_bad_epochs": self.num_bad_epochs,
            "mode_worse": self.mode_worse,
            "best": self.best,
            "last_epoch": self.last_epoch,
        }

    def set_state(self, dic: Dict) -> None:
        """Restore from a state dict (reference utils.py:90-108)."""
        for key, value in dic.items():
            setattr(self, key, value)

    def reset(self) -> None:
        """Reset the tracker (reference utils.py:109-120)."""
        self.num_bad_epochs = 0
        self.best = self.mode_worse

    def test_if_improving(self, metric: float) -> bool:
        """True if the metric has plateaued (reference utils.py:121-160)."""
        current = float(metric)
        self.last_epoch += 1
        if self.is_better(current, self.best):
            self.best = current
            self.num_bad_epochs = 0
        else:
            self.num_bad_epochs += 1
        if self.num_bad_epochs > self.patience:
            self.num_bad_epochs = 0
            return True
        return False

    def is_better(self, a: float, best: float) -> bool:
        """Comparison under the configured mode (reference utils.py:161-206)."""
        if self.mode == "min" and self.threshold_mode == "rel":
            rel_epsilon = 1.0 - self.threshold
            return a < best * rel_epsilon
        if self.mode == "min" and self.threshold_mode == "abs":
            return a < best - self.threshold
        if self.mode == "max" and self.threshold_mode == "rel":
            rel_epsilon = self.threshold + 1.0
            return a > best * rel_epsilon
        return a > best + self.threshold

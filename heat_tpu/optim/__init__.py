"""Optimizers.

The reference re-exports ``torch.optim`` attributes dynamically and adds the
data-parallel wrappers (reference heat/optim/__init__.py:18-36). The backing
optimizer library here is optax, shimmed the same way with the familiar
torch-style names: ``heat_tpu.optim.SGD(lr)`` → ``optax.sgd``, ``Adam`` →
``optax.adam``, etc. — all returning optax gradient transformations.
"""

import optax as _optax

from . import utils
from .dp_optimizer import DASO, DataParallelOptimizer
from .utils import DetectMetricPlateau

__all__ = ["DASO", "DataParallelOptimizer", "DetectMetricPlateau", "utils"]

_TORCH_STYLE = {
    "SGD": _optax.sgd,
    "Adam": _optax.adam,
    "AdamW": _optax.adamw,
    "Adagrad": _optax.adagrad,
    "RMSprop": _optax.rmsprop,
    "Adadelta": _optax.adadelta,
    "LBFGS": _optax.lbfgs,
}


def __getattr__(name):
    # dynamic fallback mirroring the reference's torch.optim shim
    # (heat/optim/__init__.py:18-36)
    if name in _TORCH_STYLE:
        return _TORCH_STYLE[name]
    try:
        return getattr(_optax, name)
    except AttributeError:
        raise AttributeError(f"module 'heat_tpu.optim' has no attribute {name!r}")

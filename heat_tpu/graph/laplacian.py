"""Graph Laplacians from similarity data (reference: heat/graph/laplacian.py)."""

from __future__ import annotations

from typing import Callable, Optional

import jax.numpy as jnp

from ..core import factories, types
from ..core.dndarray import DNDarray, _ensure_split

__all__ = ["Laplacian"]


class Laplacian:
    """Graph Laplacian of a similarity matrix (reference laplacian.py:10-141).

    Parameters
    ----------
    similarity : callable(X) -> (n, n) DNDarray
        e.g. ``lambda x: ht.spatial.rbf(x, sigma=1.0)``.
    definition : 'simple' | 'norm_sym'
    mode : 'fully_connected' | 'eNeighbour'
    threshold_key : 'upper' | 'lower'  (for eNeighbour)
    threshold_value : float
    """

    def __init__(
        self,
        similarity: Callable,
        weighted: bool = True,
        definition: str = "norm_sym",
        mode: str = "fully_connected",
        threshold_key: str = "upper",
        threshold_value: float = 1.0,
        neighbours: int = 10,
    ):
        self.similarity_metric = similarity
        self.weighted = weighted
        if definition not in ("simple", "norm_sym"):
            raise NotImplementedError(
                "Only simple and normalized symmetric graph laplacians are supported at the moment"
            )
        if mode not in ("eNeighbour", "fully_connected"):
            raise NotImplementedError(
                "Only eNeighborhood and fully-connected graphs supported at the moment."
            )
        if threshold_key not in ("upper", "lower"):
            raise ValueError(f"threshold_key must be 'upper' or 'lower', got {threshold_key}")
        self.definition = definition
        self.mode = mode
        self.epsilon = (threshold_key, threshold_value)
        self.neighbours = neighbours

    def _normalized_symmetric_L(self, A: DNDarray) -> DNDarray:
        """L_sym = I − D^−1/2 A D^−1/2 (reference laplacian.py:73-99)."""
        a = A.larray
        degree = jnp.sum(a, axis=1)
        d_inv_sqrt = jnp.where(degree > 0, 1.0 / jnp.sqrt(degree), 0.0)
        L = -a * d_inv_sqrt[:, None] * d_inv_sqrt[None, :]
        L = L.at[jnp.arange(L.shape[0]), jnp.arange(L.shape[0])].set(1.0)
        return self._wrap(L, A)

    def _simple_L(self, A: DNDarray) -> DNDarray:
        """L = D − A (reference laplacian.py:100-126)."""
        a = A.larray
        degree = jnp.sum(a, axis=1)
        L = jnp.diag(degree) - a
        return self._wrap(L, A)

    def _wrap(self, arr, ref: DNDarray) -> DNDarray:
        arr = _ensure_split(arr, ref.split, ref.comm)
        return DNDarray(
            arr, tuple(arr.shape), types.canonical_heat_type(arr.dtype), ref.split, ref.device, ref.comm
        )

    def construct(self, X: DNDarray) -> DNDarray:
        """Build the Laplacian of X's similarity graph (reference laplacian.py:127-141)."""
        S = self.similarity_metric(X)
        s = S.larray
        if self.mode == "eNeighbour":
            key, value = self.epsilon
            if key == "upper":
                s = jnp.where(s < value, s if self.weighted else 1.0, 0.0)
            else:
                s = jnp.where(s > value, s if self.weighted else 1.0, 0.0)
        # zero the self-loops
        n = s.shape[0]
        s = s.at[jnp.arange(n), jnp.arange(n)].set(0.0)
        A = self._wrap(s, S)
        if self.definition == "simple":
            return self._simple_L(A)
        return self._normalized_symmetric_L(A)

"""Distributed graph algorithms (reference: heat/graph/__init__.py)."""

from .laplacian import *

"""The heat-lint rule engine: findings, suppressions, baselines, file walking.

Pure standard-library AST analysis — importing this module never touches jax
or initializes a mesh, so ``python -m heat_tpu.analysis lint`` runs in
milliseconds on a login node with no accelerator attached. The SPMD-specific
rules themselves live in :mod:`heat_tpu.analysis.rules`; this module owns the
mechanics every rule shares:

* :class:`Finding` — one ``file:line`` diagnostic with rule id, severity,
  message and a fix hint.
* **Suppressions** — ``# heat-lint: disable=H002`` (comma-list, or ``all``)
  on the flagged line or on a standalone comment line directly above it.
  Suppressed findings are kept (``suppressed=True``) so reports can show
  what was waived, but they never fail a lint run.
* **Baselines** — a committed JSON file of fingerprinted known findings
  (:func:`write_baseline` / :func:`load_baseline` / :func:`apply_baseline`).
  Fingerprints hash (rule, path, source-line text) rather than line numbers,
  so unrelated edits above a known finding do not churn the baseline; a lint
  run against a baseline fails only on NEW findings.
"""

from __future__ import annotations

import ast
import hashlib
import json
import os
import re
from dataclasses import dataclass
from typing import Dict, Iterable, List, Sequence

__all__ = [
    "Finding",
    "LintError",
    "apply_baseline",
    "baseline_entries",
    "lint_paths",
    "lint_source",
    "load_baseline",
    "render_findings",
    "summarize",
    "write_baseline",
]

BASELINE_VERSION = 1


class LintError(RuntimeError):
    """A lint run could not complete (unreadable path, malformed baseline)."""


@dataclass
class Finding:
    """One diagnostic: ``path:line`` + rule id, severity, message, fix hint."""

    rule: str
    path: str
    line: int
    col: int
    severity: str  # "error" | "warning" | "info"
    message: str
    hint: str = ""
    source: str = ""  # the stripped source line (fingerprint input)
    suppressed: bool = False
    baselined: bool = False

    @property
    def location(self) -> str:
        return f"{self.path}:{self.line}"

    def fingerprint(self) -> str:
        """Stable identity for baseline matching: rule + path + the source
        line's text (NOT its number — edits above a known finding must not
        churn the committed baseline)."""
        raw = "|".join((self.rule, _posix(self.path), self.source))
        return hashlib.sha1(raw.encode()).hexdigest()[:16]

    def as_dict(self) -> dict:
        return {
            "rule": self.rule,
            "path": _posix(self.path),
            "line": self.line,
            "col": self.col,
            "severity": self.severity,
            "message": self.message,
            "hint": self.hint,
            "source": self.source,
            "suppressed": self.suppressed,
            "baselined": self.baselined,
            "fingerprint": self.fingerprint(),
        }


def _posix(path: str) -> str:
    return path.replace(os.sep, "/")


# ----------------------------------------------------------------------
# suppressions: # heat-lint: disable=H001[,H002] | disable=all
# ----------------------------------------------------------------------
_SUPPRESS_RE = re.compile(r"#\s*heat-lint:\s*disable=([A-Za-z0-9_,\s]+)")


def _suppressions(lines: Sequence[str]) -> Dict[int, set]:
    """1-based line -> set of suppressed rule ids ("all" wildcards)."""
    out: Dict[int, set] = {}
    for i, line in enumerate(lines, start=1):
        m = _SUPPRESS_RE.search(line)
        if m:
            out[i] = {r.strip() for r in m.group(1).split(",") if r.strip()}
    return out


def _is_suppressed(finding: Finding, sup: Dict[int, set], lines: Sequence[str]) -> bool:
    for ln in (finding.line, finding.line - 1):
        rules = sup.get(ln)
        if not rules:
            continue
        if ln == finding.line - 1:
            # a suppression one line up only applies from a standalone
            # comment line (otherwise it belongs to that line's own finding)
            text = lines[ln - 1].strip() if 0 < ln <= len(lines) else ""
            if not text.startswith("#"):
                continue
        if "all" in rules or finding.rule in rules:
            return True
    return False


# ----------------------------------------------------------------------
# linting
# ----------------------------------------------------------------------
def _resolve_rules(rules=None) -> list:
    from . import rules as rules_mod

    table = rules_mod.RULES
    if rules is None:
        return list(table)
    wanted = {r.strip().upper() for r in rules} if not isinstance(rules, str) else {
        r.strip().upper() for r in rules.split(",") if r.strip()
    }
    unknown = wanted - {r.id for r in table}
    if unknown:
        raise LintError(f"unknown rule id(s): {sorted(unknown)}")
    return [r for r in table if r.id in wanted]


def lint_source(src: str, path: str = "<string>", rules=None) -> List[Finding]:
    """Lint one Python source string. Returns every finding, with
    ``suppressed`` already resolved from ``# heat-lint: disable=`` comments;
    callers filter on it (the CLI fails only on active findings)."""
    from .rules import ModuleContext

    lines = src.splitlines()
    try:
        tree = ast.parse(src, filename=path)
    except SyntaxError as exc:
        return [
            Finding(
                rule="H000",
                path=path,
                line=int(exc.lineno or 1),
                col=int(exc.offset or 0),
                severity="error",
                message=f"file does not parse: {exc.msg}",
                hint="heat-lint analyzes the AST; fix the syntax error first",
                source=(lines[exc.lineno - 1].strip() if exc.lineno and exc.lineno <= len(lines) else ""),
            )
        ]
    ctx = ModuleContext(tree=tree, lines=lines, path=path)
    findings: List[Finding] = []
    for rule in _resolve_rules(rules):
        for line, col, message in rule.run(ctx):
            findings.append(
                Finding(
                    rule=rule.id,
                    path=path,
                    line=line,
                    col=col,
                    severity=rule.severity,
                    message=message,
                    hint=rule.hint,
                    source=(lines[line - 1].strip() if 0 < line <= len(lines) else ""),
                )
            )
    sup = _suppressions(lines)
    if sup:
        for f in findings:
            f.suppressed = _is_suppressed(f, sup, lines)
    findings.sort(key=lambda f: (f.line, f.col, f.rule))
    return findings


def _iter_py_files(paths: Iterable[str]) -> List[str]:
    out: List[str] = []
    for p in paths:
        if os.path.isfile(p):
            out.append(p)
        elif os.path.isdir(p):
            for root, dirs, files in os.walk(p):
                dirs[:] = sorted(
                    d for d in dirs if d != "__pycache__" and not d.startswith(".")
                )
                for name in sorted(files):
                    if name.endswith(".py"):
                        out.append(os.path.join(root, name))
        else:
            raise LintError(f"no such file or directory: {p!r}")
    return out


def lint_paths(paths: Iterable[str], rules=None) -> List[Finding]:
    """Lint every ``.py`` file under ``paths`` (files or directories;
    ``__pycache__`` and dot-directories are skipped). Findings are sorted by
    (path, line)."""
    findings: List[Finding] = []
    for fname in _iter_py_files(paths):
        with open(fname, "r", encoding="utf-8", errors="replace") as fh:
            src = fh.read()
        findings.extend(lint_source(src, path=_posix(fname), rules=rules))
    findings.sort(key=lambda f: (f.path, f.line, f.col, f.rule))
    return findings


# ----------------------------------------------------------------------
# baselines
# ----------------------------------------------------------------------
def baseline_entries(findings: Iterable[Finding]) -> dict:
    """The committed-baseline document for a set of findings: fingerprint
    counts for matching plus a human-reviewable entry list. Suppressed
    findings are excluded — an inline suppression already records the waiver
    next to the code it waives."""
    fps: Dict[str, int] = {}
    entries = []
    for f in findings:
        if f.suppressed:
            continue
        fps[f.fingerprint()] = fps.get(f.fingerprint(), 0) + 1
        entries.append(
            {
                "rule": f.rule,
                "path": _posix(f.path),
                "line": f.line,
                "source": f.source,
                "fingerprint": f.fingerprint(),
            }
        )
    return {"version": BASELINE_VERSION, "fingerprints": fps, "entries": entries}


def write_baseline(path: str, findings: Iterable[Finding], namespaces=None) -> dict:
    """Write ``findings`` as the committed baseline. With ``namespaces``
    (a tuple of rule-id prefixes, e.g. ``("S",)``), the write is scoped to
    those namespaces: entries of OTHER namespaces already committed at
    ``path`` are preserved verbatim, so the lint pass rewriting its H-rule
    baseline never invalidates the dataflow pass's S-rule fingerprints and
    vice versa (the two passes share one baseline file)."""
    doc = baseline_entries(findings)
    if namespaces is not None:
        prefixes = tuple(namespaces)
        doc["entries"] = [e for e in doc["entries"] if e["rule"].startswith(prefixes)]
        try:
            old = load_baseline(path)
        except LintError:
            old = None
        if old is not None:
            kept = [
                e
                for e in old.get("entries", [])
                if isinstance(e, dict) and not str(e.get("rule", "")).startswith(prefixes)
            ]
            doc["entries"] = kept + doc["entries"]
        fps: Dict[str, int] = {}
        for e in doc["entries"]:
            fp = e.get("fingerprint")
            if fp:
                fps[fp] = fps.get(fp, 0) + 1
        doc["fingerprints"] = fps
        doc["entries"].sort(key=lambda e: (e.get("path", ""), e.get("line", 0), e.get("rule", "")))
    with open(path, "w") as fh:
        json.dump(doc, fh, indent=1, sort_keys=True)
        fh.write("\n")
    return doc


def load_baseline(path: str) -> dict:
    try:
        with open(path) as fh:
            doc = json.load(fh)
    except FileNotFoundError:
        raise LintError(f"baseline file not found: {path!r} (run --write-baseline first)")
    except json.JSONDecodeError as exc:
        raise LintError(f"baseline file {path!r} is not valid JSON: {exc}")
    if not isinstance(doc, dict) or not isinstance(doc.get("fingerprints"), dict):
        raise LintError(f"baseline file {path!r} missing its fingerprints map")
    return doc


def apply_baseline(findings: Iterable[Finding], baseline: dict) -> None:
    """Mark findings present in ``baseline`` as ``baselined`` (multiset
    semantics: N identical fingerprints in the baseline absorb at most N
    findings, so a duplicated regression still surfaces)."""
    budget = dict(baseline.get("fingerprints", {}))
    for f in findings:
        if f.suppressed:
            continue
        fp = f.fingerprint()
        if budget.get(fp, 0) > 0:
            budget[fp] -= 1
            f.baselined = True


# ----------------------------------------------------------------------
# reporting
# ----------------------------------------------------------------------
def summarize(findings: Sequence[Finding]) -> dict:
    active = [f for f in findings if not f.suppressed and not f.baselined]
    return {
        "total": len(findings),
        "active": len(active),
        "errors": sum(1 for f in active if f.severity == "error"),
        "warnings": sum(1 for f in active if f.severity == "warning"),
        "suppressed": sum(1 for f in findings if f.suppressed),
        "baselined": sum(1 for f in findings if f.baselined),
        "files": len({f.path for f in active}),
    }


def render_findings(
    findings: Sequence[Finding],
    show_suppressed: bool = False,
    hints: bool = True,
    prog: str = "heat-lint",
) -> str:
    """Human-readable report: one ``path:line: RULE severity: message`` block
    per active finding (suppressed/baselined shown only on request), ending
    with a one-line summary."""
    out: List[str] = []
    for f in findings:
        if (f.suppressed or f.baselined) and not show_suppressed:
            continue
        tag = " [suppressed]" if f.suppressed else (" [baseline]" if f.baselined else "")
        out.append(f"{f.location}: {f.rule} {f.severity}: {f.message}{tag}")
        if f.source:
            out.append(f"    {f.source}")
        if hints and f.hint:
            out.append(f"    hint: {f.hint}")
    s = summarize(findings)
    out.append(
        f"{prog}: {s['active']} finding(s) ({s['errors']} error(s), "
        f"{s['warnings']} warning(s)) in {s['files']} file(s); "
        f"{s['suppressed']} suppressed, {s['baselined']} baselined"
    )
    return "\n".join(out)

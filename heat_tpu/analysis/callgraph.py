"""Call graph over the analyzed source set.

The dataflow verifier is *interprocedural*: the hazards it exists to catch
(ISSUE 9) hide behind helper-function boundaries, where PR 7's per-module
lint cannot see them. This module owns the indexing that makes cross-module
reasoning possible while staying pure standard library:

* parse every ``.py`` file under the given paths into a :class:`ModuleInfo`
  (tree, lines, import alias map, top-level functions, classes + methods);
* resolve names — ``from .basics import dot`` to the analyzed ``dot``,
  ``ht.cluster.KMeans`` through the ``heat_tpu`` alias to the analyzed
  class, ``self.fit_predict`` through the (name-resolved) class hierarchy;
* provide best-effort static call edges and a Tarjan SCC condensation so
  summaries can be computed bottom-up and recursion is detected rather than
  looped on.

Resolution is deliberately conservative: an ambiguous bare name (two
analyzed functions with the same name, neither imported here) resolves to
nothing, and the interpreter treats the call as an unknown effect-free value
— a missed finding, never a false one.
"""

from __future__ import annotations

import ast
import os
from dataclasses import dataclass, field
from typing import Dict, Iterable, List, Optional, Sequence, Tuple

from .engine import _iter_py_files, _posix

__all__ = [
    "CallGraph",
    "ClassInfo",
    "FunctionInfo",
    "ModuleInfo",
    "build",
    "module_dotted",
]


@dataclass
class FunctionInfo:
    """One analyzed function or method."""

    name: str
    qualname: str  # "<path>::fn" or "<path>::Class.fn"
    node: ast.AST  # FunctionDef | AsyncFunctionDef
    module: "ModuleInfo"
    cls: Optional[str] = None  # owning class name for methods

    def __repr__(self):
        return f"FunctionInfo({self.qualname})"


@dataclass
class ClassInfo:
    name: str
    node: ast.ClassDef
    module: "ModuleInfo"
    methods: Dict[str, FunctionInfo] = field(default_factory=dict)
    bases: List[str] = field(default_factory=list)  # base-class last names


@dataclass
class ModuleInfo:
    path: str
    dotted: str  # "heat_tpu.core.statistics" (best-effort from the path)
    tree: ast.Module
    lines: Sequence[str]
    #: local alias -> absolute dotted source: ``import heat_tpu as ht`` maps
    #: ``ht -> heat_tpu``; ``from heat_tpu.core import manipulations`` maps
    #: ``manipulations -> heat_tpu.core.manipulations``; ``from .basics
    #: import dot`` maps ``dot -> heat_tpu.core.linalg.basics.dot``
    imports: Dict[str, str] = field(default_factory=dict)
    functions: Dict[str, FunctionInfo] = field(default_factory=dict)
    classes: Dict[str, ClassInfo] = field(default_factory=dict)

    @property
    def heat_aliases(self) -> set:
        return {
            alias
            for alias, src in self.imports.items()
            if src.split(".")[0] == "heat_tpu"
        }


def module_dotted(path: str) -> str:
    """Best-effort dotted module path from a file path: the part starting at
    the last path component named like a package root (``heat_tpu``,
    ``examples``, ``tests``) — enough to resolve intra-repo imports."""
    parts = _posix(path).split("/")
    if parts[-1].endswith(".py"):
        parts[-1] = parts[-1][:-3]
    if parts[-1] == "__init__":
        parts = parts[:-1]
    for root in ("heat_tpu", "examples", "tests"):
        if root in parts:
            return ".".join(parts[parts.index(root):])
    return ".".join(parts[-2:]) if len(parts) > 1 else parts[0]


def _index_module(path: str, src: str) -> Optional[ModuleInfo]:
    try:
        tree = ast.parse(src, filename=path)
    except SyntaxError:
        return None  # the lint reports H000; the verifier just skips it
    mod = ModuleInfo(
        path=_posix(path), dotted=module_dotted(path), tree=tree, lines=src.splitlines()
    )
    pkg = mod.dotted.rsplit(".", 1)[0] if "." in mod.dotted else mod.dotted
    for node in ast.walk(tree):
        if isinstance(node, ast.Import):
            for alias in node.names:
                mod.imports[(alias.asname or alias.name).split(".")[0]] = (
                    alias.name if alias.asname else alias.name.split(".")[0]
                )
        elif isinstance(node, ast.ImportFrom) and (node.module or node.level):
            base = node.module or ""
            if node.level:  # relative: anchor at this module's package
                anchor = mod.dotted.split(".")
                anchor = anchor[: len(anchor) - node.level] or anchor[:1]
                base = ".".join(anchor + ([base] if base else []))
            for alias in node.names:
                if alias.name == "*":
                    continue
                mod.imports[alias.asname or alias.name] = f"{base}.{alias.name}"
    for node in tree.body:
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            mod.functions[node.name] = FunctionInfo(
                node.name, f"{mod.path}::{node.name}", node, mod
            )
        elif isinstance(node, ast.ClassDef):
            ci = ClassInfo(node.name, node, mod)
            ci.bases = [
                b.attr if isinstance(b, ast.Attribute) else getattr(b, "id", "")
                for b in node.bases
            ]
            for sub in node.body:
                if isinstance(sub, (ast.FunctionDef, ast.AsyncFunctionDef)):
                    ci.methods[sub.name] = FunctionInfo(
                        sub.name,
                        f"{mod.path}::{node.name}.{sub.name}",
                        sub,
                        mod,
                        cls=node.name,
                    )
            mod.classes[node.name] = ci
    return mod


class CallGraph:
    """The analyzed source set plus name-resolution services."""

    def __init__(self, modules: List[ModuleInfo]):
        self.modules: Dict[str, ModuleInfo] = {m.path: m for m in modules}
        self.by_dotted: Dict[str, ModuleInfo] = {m.dotted: m for m in modules}
        self.functions_by_name: Dict[str, List[FunctionInfo]] = {}
        self.classes_by_name: Dict[str, List[ClassInfo]] = {}
        for m in modules:
            for fn in m.functions.values():
                self.functions_by_name.setdefault(fn.name, []).append(fn)
            for ci in m.classes.values():
                self.classes_by_name.setdefault(ci.name, []).append(ci)

    # -- name resolution -------------------------------------------------
    def resolve_dotted(self, dotted: str):
        """An absolute dotted source name -> FunctionInfo | ClassInfo | None
        (``heat_tpu.core.linalg.basics.dot`` or ``examples.foo.main``)."""
        if not dotted or "." not in dotted:
            return None
        mod_path, leaf = dotted.rsplit(".", 1)
        m = self.by_dotted.get(mod_path)
        if m is not None:
            return m.functions.get(leaf) or m.classes.get(leaf)
        # package re-export (heat_tpu.cluster.KMeans defined in a submodule):
        # unique last-name match under the package prefix
        cands: List = [
            c
            for c in self.classes_by_name.get(leaf, [])
            if c.module.dotted.startswith(mod_path.split(".")[0])
        ] + [
            f
            for f in self.functions_by_name.get(leaf, [])
            if f.module.dotted.startswith(mod_path.split(".")[0])
        ]
        return cands[0] if len(cands) == 1 else None

    def resolve_name(self, module: ModuleInfo, name: str):
        """A bare name used in ``module`` -> FunctionInfo | ClassInfo | None:
        module-local definition first, then the import map."""
        hit = module.functions.get(name) or module.classes.get(name)
        if hit is not None:
            return hit
        src = module.imports.get(name)
        if src is not None:
            return self.resolve_dotted(src)
        return None

    def resolve_method(self, cls_name: str, method: str) -> Optional[FunctionInfo]:
        """Method lookup through the name-resolved class hierarchy (unique
        class names only — ambiguity resolves to nothing)."""
        seen = set()
        queue = [cls_name]
        while queue:
            cn = queue.pop(0)
            if cn in seen:
                continue
            seen.add(cn)
            cands = self.classes_by_name.get(cn, [])
            if len(cands) != 1:
                continue
            ci = cands[0]
            if method in ci.methods:
                return ci.methods[method]
            queue.extend(b for b in ci.bases if b)
        return None

    # -- static edges + SCC condensation ---------------------------------
    def static_edges(self, fn: FunctionInfo) -> List[FunctionInfo]:
        """Best-effort static call targets of one function: bare names,
        imported names, and ``self.method`` calls. Value-dependent calls are
        the interpreter's job; these edges exist for ordering and tests."""
        out: List[FunctionInfo] = []
        for node in ast.walk(fn.node):
            if not isinstance(node, ast.Call):
                continue
            f = node.func
            target = None
            if isinstance(f, ast.Name):
                target = self.resolve_name(fn.module, f.id)
            elif (
                isinstance(f, ast.Attribute)
                and isinstance(f.value, ast.Name)
                and f.value.id == "self"
                and fn.cls
            ):
                target = self.resolve_method(fn.cls, f.attr)
            elif isinstance(f, ast.Attribute) and isinstance(f.value, ast.Name):
                src = fn.module.imports.get(f.value.id)
                if src is not None:
                    target = self.resolve_dotted(f"{src}.{f.attr}")
            if isinstance(target, FunctionInfo):
                out.append(target)
            elif isinstance(target, ClassInfo):
                init = target.methods.get("__init__")
                if init is not None:
                    out.append(init)
        return out

    def all_functions(self) -> List[FunctionInfo]:
        out = []
        for m in self.modules.values():
            out.extend(m.functions.values())
            for ci in m.classes.values():
                out.extend(ci.methods.values())
        return out

    def sccs(self) -> List[List[FunctionInfo]]:
        """Tarjan SCCs of the static call graph in reverse topological order
        (callees before callers) — the summary computation order; any SCC
        with more than one member (or a self-loop) is recursion."""
        fns = self.all_functions()
        index: Dict[str, int] = {}
        low: Dict[str, int] = {}
        stack: List[FunctionInfo] = []
        on_stack = set()
        result: List[List[FunctionInfo]] = []
        counter = [0]
        edges = {f.qualname: self.static_edges(f) for f in fns}

        def strongconnect(fn: FunctionInfo):
            q = fn.qualname
            index[q] = low[q] = counter[0]
            counter[0] += 1
            stack.append(fn)
            on_stack.add(q)
            work = [(fn, iter(edges[q]))]
            while work:
                cur, it = work[-1]
                advanced = False
                for callee in it:
                    cq = callee.qualname
                    if cq not in index:
                        index[cq] = low[cq] = counter[0]
                        counter[0] += 1
                        stack.append(callee)
                        on_stack.add(cq)
                        work.append((callee, iter(edges[cq])))
                        advanced = True
                        break
                    elif cq in on_stack:
                        low[cur.qualname] = min(low[cur.qualname], index[cq])
                if advanced:
                    continue
                work.pop()
                if work:
                    parent = work[-1][0]
                    low[parent.qualname] = min(low[parent.qualname], low[cur.qualname])
                if low[cur.qualname] == index[cur.qualname]:
                    comp = []
                    while True:
                        w = stack.pop()
                        on_stack.discard(w.qualname)
                        comp.append(w)
                        if w.qualname == cur.qualname:
                            break
                    result.append(comp)

        for fn in fns:
            if fn.qualname not in index:
                strongconnect(fn)
        return result


def build(paths: Iterable[str]) -> CallGraph:
    """Parse and index every ``.py`` file under ``paths`` (same walking rules
    as the lint: ``__pycache__`` and dot-dirs skipped, unparseable files
    dropped)."""
    modules: List[ModuleInfo] = []
    for fname in _iter_py_files(paths):
        try:
            with open(fname, "r", encoding="utf-8", errors="replace") as fh:
                src = fh.read()
        except OSError:
            continue
        mod = _index_module(fname, src)
        if mod is not None:
            modules.append(mod)
    return CallGraph(modules)


def build_from_sources(sources: Dict[str, str]) -> CallGraph:
    """Index in-memory sources (tests, drift workloads): path -> source."""
    modules = []
    for path, src in sources.items():
        mod = _index_module(path, src)
        if mod is not None:
            modules.append(mod)
    return CallGraph(modules)

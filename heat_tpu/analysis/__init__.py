"""SPMD hazard analyzer: AST lint (H001-H005) + AOT sharded-program audit.

Heat's SPMD model — every host runs the same script, one ``split`` axis
expresses distribution, forcing is asynchronous — turns whole bug classes
structural: a collective under host-divergent control flow deadlocks the
mesh, an implicit blocking sync in a loop destroys the async-forcing
pipeline, a dropped sharding constraint replicates O(n) onto every host.
None of these fail a unit test; they hang or OOM at scale. This subsystem
catches them statically, in two passes:

* **Pass 1 — the lint** (:mod:`heat_tpu.analysis.rules`): a custom AST rule
  engine over Python source with SPMD-specific rules H001-H005, inline
  ``# heat-lint: disable=HXXX`` suppressions and a committed fingerprint
  baseline (:mod:`heat_tpu.analysis.engine`). Pure standard library —
  importing it never touches jax.
* **Pass 2 — the audit** (:mod:`heat_tpu.analysis.audit`): every cached
  sharded program is AOT-lowered from its abstract signature (the memoized
  ``fusion.program_costs`` machinery; nothing executes) and checked for
  replication blowups, collective-parity divergence across program
  variants, and declared bytes-on-wire budgets.
* **Pass 3 — the distribution-flow verifier**
  (:mod:`heat_tpu.analysis.dataflow`): an interprocedural abstract
  interpreter over the ``(rank, split, device-set, pending|forced)``
  lattice (:mod:`heat_tpu.analysis.lattice`), driven by a cross-module
  call graph (:mod:`heat_tpu.analysis.callgraph`) with loop widening and
  memoized per-function summaries. Rules S101-S105 catch the *semantic*
  hazards the syntactic lint cannot: implicit reshards under
  ``__binary_op``'s split dominance, blocking syncs and divergence hidden
  behind helper calls, split downgrades, and static bytes-on-wire budget
  violations — with a cost model drift-checked against telemetry's
  observed collective bytes. Pure standard library, like the lint.

``python -m heat_tpu.analysis`` is the CLI (``lint`` / ``audit`` /
``verify`` / ``rules``); ``scripts/test_matrix.sh`` runs all three passes
as its analysis leg.
"""

from .dataflow import drift_report, verify_paths, verify_source
from .engine import (
    Finding,
    LintError,
    apply_baseline,
    baseline_entries,
    lint_paths,
    lint_source,
    load_baseline,
    render_findings,
    summarize,
    write_baseline,
)
from .rules import RULES, rule_table

__all__ = [
    "AuditFinding",
    "Finding",
    "LintError",
    "RULES",
    "apply_baseline",
    "audit_programs",
    "baseline_entries",
    "drift_report",
    "lint_paths",
    "lint_source",
    "load_baseline",
    "render_findings",
    "rule_table",
    "summarize",
    "verify_paths",
    "verify_source",
    "warm_bench_cache",
    "write_baseline",
]


def __getattr__(name):
    # the audit half imports jax lazily; keep `heat_tpu.analysis` importable
    # (and the lint instant) on machines with no accelerator stack
    if name in ("AuditFinding", "audit_programs", "warm_bench_cache", "render_audit"):
        from . import audit as _audit

        return getattr(_audit, name)
    raise AttributeError(f"module {__name__!r} has no attribute {name!r}")

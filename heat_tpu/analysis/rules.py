"""The SPMD hazard rules (H001–H005).

Heat's SPMD contract — every host runs the same script, one ``split`` axis
expresses distribution, forcing is asynchronous — makes a class of
production-killing bugs *structural*, visible in the AST long before a pod
hangs. Each rule encodes one hazard (doc/internals_distribution.md "The SPMD
hazard model" is the narrative version):

========  ============================================================
H001      collective/forcing call reachable only under host-divergent
          control flow (``process_index()``/``io_owner()``/wall-clock/
          unseeded randomness): some hosts enter the collective, the
          rest never show up — the whole mesh deadlocks.
H002      implicit blocking sync inside a loop (``.item()``/``.numpy()``/
          ``float()``/``print`` of a heat value per iteration): every
          iteration fences the async-forcing pipeline PR 5 built.
H003      bare ``except Exception`` swallowing at a collective/fusion/io
          seam instead of routing through
          ``resilience.record_recoverable`` (or narrowing the type):
          real faults vanish into silent wrong-path fallbacks.
H004      per-call lambda/closure passed to ``fusion.record``/
          ``comm.apply``: the function identity churns every call, so
          the sharded-program cache misses forever (retrace churn —
          the PR 1 bug class in logical/rounding/arithmetics).
H005      declared collective schedule or reshard path without its
          ``resilience.check("collective.*")`` fault site: the fault
          harness cannot reach the seam, so recovery there is untested.
========  ============================================================

Detection is deliberately *local and conservative*: rules resolve import
aliases of the ``heat_tpu`` namespace, run a small per-function taint pass
(H001: host-divergent values; H002: heat-produced values) and otherwise
require syntactic evidence. Anything cleverer belongs in the program auditor
(:mod:`heat_tpu.analysis.audit`), which reasons about the *compiled*
artifact instead of the source, or in the distribution-flow verifier
(:mod:`heat_tpu.analysis.dataflow`, rules S101-S105), which interprets the
source *semantically* — interprocedurally, over the split lattice — and
reuses this module's syntactic vocabulary (:func:`dotted_name`,
:func:`_divergent_call`, :func:`_is_collective_call`) so the two passes
agree on what a divergence source and a collective call look like.
"""

from __future__ import annotations

import ast
from dataclasses import dataclass, field
from typing import Dict, Iterator, List, Optional, Sequence, Set, Tuple

__all__ = ["ModuleContext", "Rule", "RULES", "rule_table"]


# ----------------------------------------------------------------------
# shared AST helpers
# ----------------------------------------------------------------------
def dotted_name(node: ast.AST) -> str:
    """``a.b.c`` for Name/Attribute chains; call roots render as ``f()``
    (so ``get_comm().apply`` -> ``get_comm().apply``). Empty when the root
    is not nameable (subscripts, literals)."""
    parts: List[str] = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if isinstance(node, ast.Name):
        parts.append(node.id)
    elif isinstance(node, ast.Call):
        inner = dotted_name(node.func)
        if not inner:
            return ""
        parts.append(inner + "()")
    else:
        return ""
    return ".".join(reversed(parts))


def last_name(node: ast.AST) -> str:
    if isinstance(node, ast.Attribute):
        return node.attr
    if isinstance(node, ast.Name):
        return node.id
    return ""


def _assigned_names(target: ast.AST) -> Iterator[str]:
    if isinstance(target, ast.Name):
        yield target.id
    elif isinstance(target, (ast.Tuple, ast.List)):
        for elt in target.elts:
            yield from _assigned_names(elt)
    elif isinstance(target, ast.Starred):
        yield from _assigned_names(target.value)


def _function_units(tree: ast.Module):
    """The analysis units: the module top level (examples are scripts!) and
    every function/method body, each yielded as (name, body_statements)."""
    yield "<module>", tree.body
    for node in ast.walk(tree):
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            yield node.name, node.body


def unit_walk(stmts: Sequence[ast.stmt]) -> Iterator[ast.AST]:
    """``ast.walk`` over a statement list WITHOUT descending into nested
    function/class definitions — each of those is its own analysis unit
    (walking into them here would double-report and cross-taint)."""
    stack: List[ast.AST] = list(stmts)
    while stack:
        node = stack.pop()
        yield node
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef, ast.ClassDef, ast.Lambda)):
            continue  # yielded (so rules can see it) but never expanded
        stack.extend(ast.iter_child_nodes(node))


@dataclass
class ModuleContext:
    """Everything a rule needs about one module: the tree, the raw source
    lines, the path, and the resolved root aliases of the ``heat_tpu``
    namespace (``import heat_tpu as ht`` / ``from heat_tpu import ...``)."""

    tree: ast.Module
    lines: Sequence[str]
    path: str
    heat_aliases: Set[str] = field(default_factory=set)

    def __post_init__(self):
        # only whole-package imports (``import heat_tpu as ht``) seed the
        # H002 taint: that is how user scripts hold the array API, and it
        # keeps ``from heat_tpu.core import <internals>`` plumbing (which
        # mostly returns non-array values) from polluting the heuristic
        for node in ast.walk(self.tree):
            if isinstance(node, ast.Import):
                for alias in node.names:
                    if alias.name.split(".")[0] == "heat_tpu":
                        self.heat_aliases.add((alias.asname or alias.name).split(".")[0])


@dataclass
class Rule:
    id: str
    severity: str
    title: str
    rationale: str
    hint: str
    checker: object = None

    def run(self, ctx: ModuleContext) -> Iterator[Tuple[int, int, str]]:
        return self.checker(ctx)


# ----------------------------------------------------------------------
# H001 — collectives/forcing under host-divergent control flow
# ----------------------------------------------------------------------
#: call names (last attribute) whose result differs across controller
#: processes of one SPMD job
_DIVERGENT_LAST = {"process_index", "io_owner", "getpid", "gethostname"}
#: dotted forms for wall-clock reads (``time`` alone is too generic)
_DIVERGENT_DOTTED = {
    "time.time",
    "time.time_ns",
    "time.perf_counter",
    "time.perf_counter_ns",
    "time.monotonic",
    "datetime.now",
    "datetime.datetime.now",
    "os.getpid",
    "socket.gethostname",
}
#: the stdlib/numpy GLOBAL RNGs draw from per-process state — unseeded by
#: construction. (`random.Random(seed)` / `np.random.default_rng(seed)`
#: objects are fine and not matched.)
_DIVERGENT_RNG_ROOTS = ("random.", "np.random.", "numpy.random.")

#: mesh-spanning calls: if only SOME hosts reach one, the others never join
_COLLECTIVE_LAST = {
    "allreduce",
    "allgather",
    "alltoall",
    "ppermute",
    "exscan",
    "psum",
    "pmean",
    "pmax",
    "pmin",
    "all_gather",
    "all_to_all",
    "reduce_scatter",
    "sync_processes",
    "sync_global_devices",
    "resplit",
    "resplit_",
}
#: names too generic to match alone — the receiver chain must look like a
#: communication context (``comm.apply``, ``self.comm.bcast``,
#: ``get_comm().scan``)
_COLLECTIVE_COMM_ONLY = {"apply", "bcast", "scan", "barrier"}
#: host boundaries that force (and therefore dispatch) a possibly
#: collective-bearing fused program
_FORCING_ATTRS = {"parray", "larray"}
_FORCING_METHODS = {"item", "numpy"}


def _comm_receiver(func: ast.AST) -> bool:
    dotted = dotted_name(func)
    head = dotted.rsplit(".", 1)[0] if "." in dotted else ""
    return (
        "comm" in head
        or "communication" in head
        or head.endswith("get_comm()")
    )


def _is_collective_call(call: ast.Call) -> bool:
    name = last_name(call.func)
    if name in _COLLECTIVE_LAST:
        return True
    return name in _COLLECTIVE_COMM_ONLY and _comm_receiver(call.func)


def _divergent_call(call: ast.Call) -> bool:
    name = last_name(call.func)
    dotted = dotted_name(call.func)
    if name in _DIVERGENT_LAST or dotted in _DIVERGENT_DOTTED:
        return True
    if dotted.startswith(_DIVERGENT_RNG_ROOTS):
        # global-RNG draws; default_rng(seed)/Random(seed) construction is
        # deterministic and exempt, a bare default_rng() is OS-seeded
        if name in {"default_rng", "Random", "RandomState"}:
            return not call.args and not call.keywords
        return name not in {"seed"}
    return False


def _divergent_names(body: Sequence[ast.stmt]) -> Set[str]:
    """Names in this unit bound (transitively) from a host-divergent call."""
    tainted: Set[str] = set()

    def expr_divergent(expr: ast.AST) -> bool:
        for sub in ast.walk(expr):
            if isinstance(sub, ast.Call) and _divergent_call(sub):
                return True
            if isinstance(sub, ast.Name) and sub.id in tainted:
                return True
        return False

    for _ in range(8):  # tiny fixpoint: assignment chains are short
        changed = False
        for node in unit_walk(body):
            targets = []
            if isinstance(node, ast.Assign):
                targets, value = node.targets, node.value
            elif isinstance(node, (ast.AugAssign, ast.AnnAssign, ast.NamedExpr)):
                targets, value = [node.target], node.value
            else:
                continue
            if value is None or not expr_divergent(value):
                continue
            for t in targets:
                for name in _assigned_names(t):
                    if name not in tainted:
                        tainted.add(name)
                        changed = True
        if not changed:
            break
    return tainted


def _terminates(stmts: Sequence[ast.stmt]) -> bool:
    return bool(stmts) and isinstance(
        stmts[-1], (ast.Return, ast.Raise, ast.Continue, ast.Break)
    )


def _h001(ctx: ModuleContext) -> Iterator[Tuple[int, int, str]]:
    for unit_name, body in _function_units(ctx.tree):
        tainted = _divergent_names(body)

        def test_divergent(expr: ast.AST) -> bool:
            for sub in ast.walk(expr):
                if isinstance(sub, ast.Call) and _divergent_call(sub):
                    return True
                if isinstance(sub, ast.Name) and sub.id in tainted:
                    return True
            return False

        reported: Set[int] = set()

        def hazards(stmt: ast.stmt, why: str) -> Iterator[Tuple[int, int, str]]:
            for sub in ast.walk(stmt):
                if id(sub) in reported:
                    continue
                msg = None
                if isinstance(sub, ast.Call) and _is_collective_call(sub):
                    msg = (
                        f"collective `{dotted_name(sub.func) or last_name(sub.func)}` is "
                        f"reachable only under host-divergent control flow ({why}): hosts "
                        "that skip this branch never join the collective — the mesh "
                        "deadlocks"
                    )
                elif isinstance(sub, ast.Call) and last_name(sub.func) in _FORCING_METHODS:
                    msg = (
                        f"`.{last_name(sub.func)}()` forces (and dispatches a possibly "
                        f"collective-bearing fused program) only under host-divergent "
                        f"control flow ({why}) — a multihost deadlock hazard"
                    )
                elif isinstance(sub, ast.Attribute) and sub.attr in _FORCING_ATTRS:
                    msg = (
                        f"`.{sub.attr}` forcing access under host-divergent control flow "
                        f"({why}): the dispatched program's collectives run on a subset "
                        "of hosts — a multihost deadlock hazard"
                    )
                if msg is not None:
                    reported.add(id(sub))
                    yield sub.lineno, sub.col_offset, msg

        def walk_block(stmts: Sequence[ast.stmt], divergent: Optional[str]) -> Iterator:
            guard: Optional[str] = None  # early-exit divergence within this block
            for stmt in stmts:
                why = divergent or guard
                if isinstance(stmt, (ast.If, ast.While)):
                    branch_why = why
                    if test_divergent(stmt.test):
                        branch_why = branch_why or f"branch on line {stmt.lineno}'s test"
                        # `if owner: return` — everything after runs on the
                        # OTHER hosts only: the rest of this block diverges
                        if isinstance(stmt, ast.If) and _terminates(stmt.body):
                            guard = guard or f"early exit on line {stmt.lineno}"
                    yield from walk_block(stmt.body, branch_why)
                    yield from walk_block(stmt.orelse, branch_why)
                elif isinstance(stmt, (ast.For, ast.AsyncFor)):
                    yield from walk_block(stmt.body, why)
                    yield from walk_block(stmt.orelse, why)
                elif isinstance(stmt, (ast.With, ast.AsyncWith)):
                    yield from walk_block(stmt.body, why)
                elif isinstance(stmt, ast.Try):
                    yield from walk_block(stmt.body, why)
                    for h in stmt.handlers:
                        yield from walk_block(h.body, why)
                    yield from walk_block(stmt.orelse, why)
                    yield from walk_block(stmt.finalbody, why)
                elif isinstance(stmt, (ast.FunctionDef, ast.AsyncFunctionDef, ast.ClassDef)):
                    continue  # nested defs are their own analysis unit
                elif why:
                    yield from hazards(stmt, why)
                # statements *inside* a divergent If/While were handled via
                # the recursive calls above; the If/While line itself (its
                # test) cannot contain a collective worth re-reporting

        yield from walk_block(body, None)


# ----------------------------------------------------------------------
# H002 — implicit blocking syncs inside loops
# ----------------------------------------------------------------------
def _heat_tainted_names(ctx: ModuleContext, body: Sequence[ast.stmt]) -> Set[str]:
    """Names bound (transitively) from the heat_tpu namespace in this unit:
    ``x = ht.mean(a)``; ``y = x + 1``; ``z = y.sum()`` are all tainted."""
    tainted: Set[str] = set()
    if not ctx.heat_aliases:
        return tainted

    def expr_tainted(expr: ast.AST) -> bool:
        for sub in ast.walk(expr):
            if isinstance(sub, ast.Name) and sub.id in tainted:
                return True
            if isinstance(sub, ast.Call):
                root = dotted_name(sub.func).split(".")[0]
                if root in ctx.heat_aliases:
                    return True
        return False

    for _ in range(8):
        changed = False
        for node in unit_walk(body):
            targets = []
            if isinstance(node, ast.Assign):
                targets, value = node.targets, node.value
            elif isinstance(node, (ast.AugAssign, ast.AnnAssign, ast.NamedExpr)):
                targets, value = [node.target], node.value
            elif isinstance(node, (ast.For, ast.AsyncFor)):
                targets, value = [node.target], node.iter
            else:
                continue
            if value is None or not expr_tainted(value):
                continue
            for t in targets:
                for name in _assigned_names(t):
                    if name not in tainted:
                        tainted.add(name)
                        changed = True
        if not changed:
            break
    return tainted


_SYNC_CASTS = {"float", "int", "bool", "complex"}


def _h002(ctx: ModuleContext) -> Iterator[Tuple[int, int, str]]:
    if not ctx.heat_aliases:
        return  # the rule tracks values produced by the heat_tpu namespace
    for unit_name, body in _function_units(ctx.tree):
        tainted = _heat_tainted_names(ctx, body)

        def expr_tainted(expr: ast.AST) -> bool:
            for sub in ast.walk(expr):
                if isinstance(sub, ast.Name) and sub.id in tainted:
                    return True
                if isinstance(sub, ast.Call):
                    root = dotted_name(sub.func).split(".")[0]
                    if root in ctx.heat_aliases:
                        return True
            return False

        def sinks(node: ast.AST) -> Iterator[Tuple[int, int, str]]:
            for sub in unit_walk([node]):
                if not isinstance(sub, ast.Call):
                    continue
                name = last_name(sub.func)
                if (
                    isinstance(sub.func, ast.Attribute)
                    and name in _FORCING_METHODS
                    and expr_tainted(sub.func.value)
                ):
                    yield sub.lineno, sub.col_offset, (
                        f"`.{name}()` on a heat array inside a loop blocks on the device "
                        "every iteration — it forces the pending chain and fences the "
                        "async-forcing pipeline"
                    )
                elif (
                    isinstance(sub.func, ast.Name)
                    and name in _SYNC_CASTS
                    and any(expr_tainted(a) for a in sub.args)
                ):
                    yield sub.lineno, sub.col_offset, (
                        f"`{name}()` of a heat array inside a loop is an implicit blocking "
                        "sync every iteration (scalar host read)"
                    )
                elif (
                    isinstance(sub.func, ast.Name)
                    and name == "print"
                    and any(expr_tainted(a) for a in sub.args)
                ):
                    yield sub.lineno, sub.col_offset, (
                        "`print` of a heat array inside a loop forces and host-reads the "
                        "value every iteration — an implicit blocking sync"
                    )

        seen: Set[Tuple[int, int]] = set()
        for stmt in unit_walk(body):
            if isinstance(stmt, (ast.For, ast.AsyncFor, ast.While)):
                nodes: List[ast.AST] = list(stmt.body)
                if isinstance(stmt, ast.While):
                    nodes.append(stmt.test)  # re-evaluated every iteration
                for node in nodes:
                    for line, col, msg in sinks(node):
                        if (line, col) not in seen:
                            seen.add((line, col))
                            yield line, col, msg


# ----------------------------------------------------------------------
# H003 — bare `except Exception` swallowing at collective/fusion/io seams
# ----------------------------------------------------------------------
_FUSION_SEAM = {
    "record",
    "force",
    "defer_apply",
    "defer_reshard",
    "defer_binary",
    "defer_local",
    "defer_reduce",
    "defer_cum",
}
_IO_SEAM = {
    "open",
    "replace",
    "rename",
    "unlink",
    "remove",
    "rmtree",
    "copy2",
    "copyfile",
    "makedirs",
    "mkdir",
    "memmap",
    "fromfile",
    "tofile",
    "run",  # subprocess.run — the native-toolchain seam
    "call_with_retries",  # the resilience-retried io call wrapper
    "atomic_write",
}
_SHARDING_SEAM = {"device_put", "with_sharding_constraint", "is_equivalent_to"}


def _seam_calls(stmts: Sequence[ast.stmt]) -> List[str]:
    out = []
    for stmt in stmts:
        for sub in ast.walk(stmt):
            if isinstance(sub, ast.Call):
                name = last_name(sub.func)
                dotted = dotted_name(sub.func)
                if name in _FUSION_SEAM or name in _SHARDING_SEAM:
                    out.append(dotted or name)
                elif name in _COLLECTIVE_LAST or (
                    name in _COLLECTIVE_COMM_ONLY and _comm_receiver(sub.func)
                ):
                    out.append(dotted or name)
                elif name in _IO_SEAM:
                    if name == "run" and "subprocess" not in dotted:
                        continue
                    out.append(dotted or name)
                elif dotted.startswith("_native.") or "._native" in dotted:
                    out.append(dotted)
            elif isinstance(sub, ast.Attribute) and sub.attr == "distributed":
                out.append(dotted_name(sub))  # distributed-runtime state probe
    return out


def _broad_handler(handler: ast.ExceptHandler) -> bool:
    t = handler.type
    if t is None:
        return True
    names = [last_name(e) for e in t.elts] if isinstance(t, ast.Tuple) else [last_name(t)]
    return any(n in ("Exception", "BaseException") for n in names)


def _handler_accounts(handler: ast.ExceptHandler) -> bool:
    """Whether the handler deals with the failure instead of swallowing it:
    re-raises, routes through the resilience policy, warns, records
    telemetry, or at least *uses* the caught exception object."""
    exc_name = handler.name
    for sub in ast.walk(ast.Module(body=handler.body, type_ignores=[])):
        if isinstance(sub, ast.Raise):
            return True
        if isinstance(sub, ast.Call):
            name = last_name(sub.func)
            if name in (
                "record_recoverable",
                "force_recoverable",
                "record_unfused",
                "record_io_retry",
                "record_fault",
                "warn",
            ):
                return True
        if exc_name and isinstance(sub, ast.Name) and sub.id == exc_name:
            return True
    return False


def _h003(ctx: ModuleContext) -> Iterator[Tuple[int, int, str]]:
    for node in ast.walk(ctx.tree):
        if not isinstance(node, ast.Try):
            continue
        seams = _seam_calls(node.body)
        if not seams:
            continue
        for handler in node.handlers:
            if not _broad_handler(handler) or _handler_accounts(handler):
                continue
            what = "bare `except:`" if handler.type is None else "`except Exception`"
            yield handler.lineno, handler.col_offset, (
                f"{what} silently swallows failures of a "
                f"collective/fusion/io seam (`{seams[0]}`): narrow the exception "
                "type, or route the decision through "
                "`resilience.record_recoverable` so real faults propagate"
            )


# ----------------------------------------------------------------------
# H004 — per-call lambdas/closures into the program-cache seams
# ----------------------------------------------------------------------
def _h004_sink(call: ast.Call) -> Optional[str]:
    name = last_name(call.func)
    dotted = dotted_name(call.func)
    if name == "record" and (dotted == "record" or dotted.endswith("fusion.record")):
        return dotted or "record"
    if name == "defer_apply":
        return dotted or "defer_apply"
    if name == "apply" and _comm_receiver(call.func):
        return dotted or "comm.apply"
    return None


def _h004(ctx: ModuleContext) -> Iterator[Tuple[int, int, str]]:
    # units overlap on nested defs (a closure passed to a sink is visible
    # from its own unit AND every enclosing one — which is what lets the
    # rule see outer-local names); report each argument site exactly once
    reported: Set[Tuple[int, int]] = set()
    for unit_name, body in _function_units(ctx.tree):
        if unit_name == "<module>":
            continue  # module-level lambdas are created once per process
        # names bound per-call: lambdas assigned in this body, and nested defs
        local_fns: Set[str] = set()
        for stmt in body:
            for sub in ast.walk(stmt):
                if isinstance(sub, (ast.FunctionDef, ast.AsyncFunctionDef)):
                    local_fns.add(sub.name)
                elif isinstance(sub, ast.Assign) and isinstance(sub.value, ast.Lambda):
                    for t in sub.targets:
                        local_fns.update(_assigned_names(t))
        for stmt in body:
            for sub in ast.walk(stmt):
                if not isinstance(sub, ast.Call):
                    continue
                sink = _h004_sink(sub)
                if sink is None:
                    continue
                for arg in list(sub.args) + [kw.value for kw in sub.keywords]:
                    at = (arg.lineno, arg.col_offset)
                    if at in reported:
                        continue
                    if isinstance(arg, ast.Lambda):
                        reported.add(at)
                        yield at[0], at[1], (
                            f"lambda created per call and passed to `{sink}`: its identity "
                            "keys the sharded-program cache, so every call retraces and "
                            "recompiles (retrace churn)"
                        )
                    elif isinstance(arg, ast.Name) and arg.id in local_fns:
                        reported.add(at)
                        yield at[0], at[1], (
                            f"`{arg.id}` is defined inside this function and passed to "
                            f"`{sink}`: a fresh closure per call churns the program cache "
                            "(every call retraces)"
                        )


# ----------------------------------------------------------------------
# H005 — collective schedule / reshard path without its fault site
# ----------------------------------------------------------------------
_H005_TRIGGERS = {"record_collective", "record_collective_operand", "defer_reshard"}
#: the definitions themselves (telemetry/fusion) are not call sites
_H005_EXEMPT_FUNCS = _H005_TRIGGERS


def _h005(ctx: ModuleContext) -> Iterator[Tuple[int, int, str]]:
    for node in ast.walk(ctx.tree):
        if not isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            continue
        if node.name in _H005_EXEMPT_FUNCS:
            continue
        trigger: Optional[ast.Call] = None
        trigger_name = ""
        guarded = False
        for sub in ast.walk(node):
            if not isinstance(sub, ast.Call):
                continue
            name = last_name(sub.func)
            if name in _H005_TRIGGERS and trigger is None:
                trigger, trigger_name = sub, name
            elif name == "check" and sub.args:
                arg = sub.args[0]
                if isinstance(arg, ast.Constant) and isinstance(arg.value, str) and arg.value.startswith("collective."):
                    guarded = True
            elif name == "check_fault_site":  # future-proof alias
                guarded = True
        if trigger is not None and not guarded:
            yield trigger.lineno, trigger.col_offset, (
                f"`{trigger_name}` declares a collective schedule (or records a "
                "reshard) but the function carries no "
                '`resilience.check("collective.<verb>")` fault site: the fault '
                "harness cannot reach this seam, so its failure path is untestable"
            )


# ----------------------------------------------------------------------
# the registry
# ----------------------------------------------------------------------
RULES: List[Rule] = [
    Rule(
        id="H001",
        severity="error",
        title="collective under host-divergent control flow",
        rationale=(
            "SPMD requires every host to reach every collective; a branch on "
            "process identity, wall-clock or unseeded randomness sends only "
            "some hosts in — the rest wait forever (mesh deadlock)"
        ),
        hint=(
            "hoist the collective/forcing call out of the divergent branch "
            "(compute on all hosts, gate only the pure-file-I/O publication on "
            "io_owner()), or derive the branch from data every host shares"
        ),
        checker=_h001,
    ),
    Rule(
        id="H002",
        severity="warning",
        title="implicit blocking sync inside a loop",
        rationale=(
            "forcing is asynchronous (PR 5): dispatches install futures and only "
            "host reads block. An .item()/float()/print of a heat value per "
            "iteration re-fences the pipeline every step, serializing the loop "
            "at one dispatch RTT per iteration"
        ),
        hint=(
            "keep per-iteration results recorded and read them once after the "
            "loop; if a per-iteration host read is the point (convergence "
            "checks), suppress with `# heat-lint: disable=H002` + justification"
        ),
        checker=_h002,
    ),
    Rule(
        id="H003",
        severity="warning",
        title="bare except swallowing at a collective/fusion/io seam",
        rationale=(
            "a swallowed seam failure silently reroutes real faults (OOM, dead "
            "host, corrupt file) into wrong-path fallbacks; the resilience layer "
            "owns ONE policy for what may fall back (record_recoverable) and "
            "what must propagate"
        ),
        hint=(
            "narrow the except to the exact failure the fallback handles, or "
            "route through `resilience.record_recoverable(exc)`; if swallowing "
            "IS the contract, add `# heat-lint: disable=H003` with a reason"
        ),
        checker=_h003,
    ),
    Rule(
        id="H004",
        severity="warning",
        title="per-call lambda/closure keys the program cache",
        rationale=(
            "fusion's program cache and the retrace ledger key on function "
            "identity; a lambda or nested def created per call never matches, "
            "so every call pays a fresh trace+compile (the PR 1 bug class in "
            "logical/rounding/arithmetics)"
        ),
        hint=(
            "hoist the callable to module level, or build it once through an "
            "lru_cache'd factory (see fusion._apply_fn / statistics."
            "_arg_reduce_kernel) so its identity is stable across calls"
        ),
        checker=_h004,
    ),
    Rule(
        id="H005",
        severity="warning",
        title="collective schedule without its fault-injection site",
        rationale=(
            "every collective verb and reshard path carries a named "
            "resilience.check site so the fault harness can prove what happens "
            "when it fails; a declared schedule without one is a seam the "
            "kill-a-host test can never exercise"
        ),
        hint=(
            'add `if resilience._ARMED: resilience.check("collective.<verb>")` '
            "next to the dispatch the schedule declares (see core/communication"
            ".py's verbs for the pattern)"
        ),
        checker=_h005,
    ),
]


def rule_table() -> List[dict]:
    """The rule registry as documentation-ready dicts (the CLI's ``rules``
    subcommand and the README table source)."""
    return [
        {
            "id": r.id,
            "severity": r.severity,
            "title": r.title,
            "rationale": r.rationale,
            "hint": r.hint,
        }
        for r in RULES
    ]

"""The distribution-flow verifier: an interprocedural abstract interpreter.

PR 7's lint (H001–H005) is intraprocedural and syntactic; the expensive bug
class it cannot see is *semantic*. Heat's single-integer ``split`` makes
distribution statically decidable (HeAT, arxiv 2007.13552), and the
split-changing operations are where the collective cost lives (arxiv
2112.01075 prices every split→split change): mixed-split operands silently
resharded by XLA inside ``__binary_op``'s split-dominance rule
(``heat_tpu/core/_operations.py``), forcing points hidden behind helper
boundaries, estimator loops whose on-wire bytes nobody can bound before
running. This module interprets Python ASTs over the
:mod:`~heat_tpu.analysis.lattice` domain — ``(rank, split ∈ {None, 0..k,
⊤}, device-set, pending|forced)`` — interprocedurally via the
:mod:`~heat_tpu.analysis.callgraph`, with loop widening and memoized
per-function summaries, and reports four semantic rules through the
existing :class:`~heat_tpu.analysis.engine.Finding` machinery:

========  ============================================================
S101      implicit reshard: a binary/``where``/``out=`` op whose
          inferred operand splits are *concrete and different* — split
          dominance makes XLA reshard the non-dominant side invisibly
          (no ``collective.reshard`` fault site, no telemetry bytes,
          no fusion ``defer_reshard`` node), reported with a static
          bytes-moved estimate.
S102      interprocedural blocking-sync-in-loop: a loop calls a helper
          whose summary (transitively) blocks on the device — H002's
          hazard carried through call summaries.
S103      split-downgrade: an explicit resplit to ``None`` of a value
          whose inferred split is a concrete axis — the array
          materializes O(n) on every host where a sharded layout was
          available.
S104      interprocedural divergence: lockstep two-abstract-host
          reasoning extending H001 across function boundaries — a
          divergent branch calls a helper that reaches a collective/
          forcing point, or the divergence itself came out of a
          callee's return value.
S105      static collective-cost budget exceeded: a region's
          bytes-on-wire lower bound (the op-table cost model over the
          lattice state) breaks a declared ``--budget GLOB=BYTES``.
========  ============================================================

The cost model's byte conventions deliberately match telemetry's
logical-payload accounting (``record_collective_operand`` and the linalg
declared schedules), so the **drift check** can diff static estimates
against ``telemetry.collectives()`` observed bytes on the same workloads
(:data:`DRIFT_WORKLOADS`) — the model cannot silently rot.

Pure standard library at import time; only the drift *runner*
(:func:`observed_workload_bytes`) touches jax, lazily.
"""

from __future__ import annotations

import ast
import fnmatch
import re
from dataclasses import dataclass, field, replace
from typing import Dict, List, Optional, Sequence, Tuple

from . import callgraph as cg
from . import lattice as lat
from .engine import Finding, _is_suppressed, _posix, _suppressions
from .lattice import TOP, UNKNOWN, AbstractArray, Const, Instance, Scalar, VTuple
from .rules import (
    Rule,
    _divergent_call,
    _is_collective_call,
    dotted_name,
    last_name,
)

__all__ = [
    "DRIFT_WORKLOADS",
    "RULES",
    "drift_report",
    "observed_workload_bytes",
    "parse_budget_arg",
    "rule_table",
    "static_workload_bytes",
    "verify_paths",
    "verify_source",
    "workload_source",
]

DEFAULT_MESH_SIZE = 8
#: loop bodies re-interpret until the widened env is stable, at most this
MAX_LOOP_ITERS = 3
#: distinct abstract calling contexts memoized per function before falling
#: back to the context-insensitive (all-UNKNOWN) summary
MAX_CONTEXTS = 8
#: interpretation depth cap (recursion guard for un-memoized instance calls)
MAX_CALL_DEPTH = 40
#: the acceptance bound for the static-vs-observed drift check: estimates
#: must sit within this factor of telemetry-observed bytes
DRIFT_FACTOR = 2.0

#: collective op types whose *observed* bytes telemetry records (the verbs +
#: declared linalg schedules); the drift check compares exactly these
OBSERVED_OPS = ("allreduce", "allgather", "alltoall", "ppermute", "bcast", "exscan", "scan")


# ----------------------------------------------------------------------
# the semantic rule registry (metadata; detection lives in the interpreter)
# ----------------------------------------------------------------------
RULES: List[Rule] = [
    Rule(
        id="S101",
        severity="error",
        title="implicit reshard at a mixed-split operation",
        rationale=(
            "split dominance (core/_operations.py __binary_op) distributes a "
            "binary result along the first operand's split and reshards the "
            "other side during the op: identical-shape combinations now ride "
            "the explicit resplit seam (fault site + telemetry bytes + "
            "fusion node), broadcasted ones XLA reshards invisibly — and "
            "either way the bytes move, silently from the SOURCE's point of "
            "view, on every single call"
        ),
        hint=(
            "make the reshard explicit: `b = ht.resplit(b, a.split)` (a "
            "recorded DAG node with its fault site and telemetry bytes) "
            "before the op, or suppress with `# heat-lint: disable=S101` + "
            "a justification that the implicit reshard is intended"
        ),
    ),
    Rule(
        id="S102",
        severity="warning",
        title="blocking sync in a loop through a helper call",
        rationale=(
            "H002 sees `.item()`/`float()` in the loop body; it cannot see a "
            "helper whose *summary* blocks. Each iteration still fences the "
            "async-forcing pipeline — the hazard just moved behind a "
            "function boundary"
        ),
        hint=(
            "hoist the host read out of the loop, return the recorded (un-"
            "forced) value from the helper, or suppress with "
            "`# heat-lint: disable=S102` + why the per-iteration read is "
            "the point (convergence checks)"
        ),
    ),
    Rule(
        id="S103",
        severity="warning",
        title="split downgrade to replicated",
        rationale=(
            "a resplit to None of a value whose inferred split is a concrete "
            "axis materializes the full array on every host (an allgather "
            "and O(n) per-host memory) on a path where a sharded layout was "
            "available — the replication blowup the AOT auditor sees in "
            "compiled programs, caught here at the source"
        ),
        hint=(
            "keep the sharded layout and resplit only the (small) final "
            "result, or suppress with `# heat-lint: disable=S103` + why the "
            "gather is intended (small arrays, host export)"
        ),
    ),
    Rule(
        id="S104",
        severity="error",
        title="interprocedural host-divergent collective",
        rationale=(
            "lockstep two-abstract-host execution: on a branch whose "
            "condition differs across hosts, one abstract host calls a "
            "helper that reaches a collective/forcing point and the other "
            "never does — the mesh deadlocks. H001 sees this only when both "
            "the divergence and the collective are in one function; this "
            "rule carries both through call summaries"
        ),
        hint=(
            "hoist the helper call out of the divergent branch (compute on "
            "all hosts, gate only pure file I/O on io_owner()), or derive "
            "the branch from data every host shares"
        ),
    ),
    Rule(
        id="S105",
        severity="error",
        title="static collective-cost budget exceeded",
        rationale=(
            "the per-region cost model (op table x lattice state) lower-"
            "bounds bytes-on-wire before anything runs; a region over its "
            "declared --budget GLOB=BYTES ceiling ships a collective bill "
            "nobody signed off on"
        ),
        hint=(
            "cut the reshards/gathers the verify report itemizes for the "
            "region, or raise the budget deliberately in the CI invocation"
        ),
    ),
]


def rule_table() -> List[dict]:
    """The dataflow pass's rule registry, documentation-ready (the CLI
    ``rules`` verb prints it below the lint pass's table)."""
    return [
        {
            "id": r.id,
            "severity": r.severity,
            "title": r.title,
            "rationale": r.rationale,
            "hint": r.hint,
        }
        for r in RULES
    ]


_RULE_BY_ID = {r.id: r for r in RULES}


# ----------------------------------------------------------------------
# small shared helpers
# ----------------------------------------------------------------------
_DTYPE_NAMES = set(lat._ITEMSIZE)


def _dtype_from_node(node: Optional[ast.AST]) -> Optional[str]:
    """``ht.float64`` / ``types.float32`` kwarg ASTs -> dtype name."""
    if node is None:
        return None
    name = last_name(node)
    if name in _DTYPE_NAMES:
        return name
    if isinstance(node, ast.Constant) and isinstance(node.value, str):
        return node.value if node.value in _DTYPE_NAMES else None
    return None


_DTYPE_ORDER = [
    "bool", "uint8", "int8", "int16", "uint16", "int32", "uint32", "int64",
    "uint64", "bfloat16", "float16", "float32", "float64", "complex64",
    "complex128",
]


def _promote(d1: Optional[str], d2: Optional[str]) -> Optional[str]:
    if d1 is None or d2 is None:
        return d1 or d2
    if d1 not in _DTYPE_ORDER or d2 not in _DTYPE_ORDER:
        return None
    return max(d1, d2, key=_DTYPE_ORDER.index)


def _const_int(v) -> Optional[int]:
    if isinstance(v, Const) and isinstance(v.value, int) and not isinstance(v.value, bool):
        return v.value
    return None


def _const_shape(v) -> Optional[Tuple[int, ...]]:
    """A shape argument's statically-known dims, or None."""
    if isinstance(v, Const):
        if isinstance(v.value, int) and not isinstance(v.value, bool):
            return (v.value,)
        if isinstance(v.value, (tuple, list)) and all(
            isinstance(d, int) and not isinstance(d, bool) for d in v.value
        ):
            return tuple(v.value)
    if isinstance(v, VTuple):
        dims = [_const_int(i) for i in v.items]
        if all(d is not None for d in dims):
            return tuple(dims)
    return None


def _norm_split(split: lat.Split, rank: Optional[int]) -> lat.Split:
    """Normalize a negative split axis against a known rank (the runtime's
    sanitize_axis does the same): ``split=-1`` on a rank-2 array IS axis 1,
    and two spellings of one axis must not read as disagreement. Unknown
    rank keeps the raw value; out-of-range goes to ⊤ (the runtime would
    raise — not this pass's finding)."""
    if isinstance(split, int) and rank:
        if -rank <= split < rank:
            return split % rank
        return TOP
    return split


def _split_arg(v, present: bool) -> lat.Split:
    """A ``split=`` argument value -> the split sub-lattice (absent/None
    defaults to replicated, which is every factory's default)."""
    if not present:
        return None
    if isinstance(v, Const):
        if v.value is None:
            return None
        if isinstance(v.value, int) and not isinstance(v.value, bool):
            return v.value
    return TOP


def _terminates(stmts: Sequence[ast.stmt]) -> bool:
    return bool(stmts) and isinstance(
        stmts[-1], (ast.Return, ast.Raise, ast.Continue, ast.Break)
    )


def _ceil_div(a: int, b: int) -> int:
    return -(-a // b)


# ----------------------------------------------------------------------
# interpretation state
# ----------------------------------------------------------------------
def _costlier_path(base: Dict[str, int], a: Dict[str, int], b: Dict[str, int]) -> Dict[str, int]:
    """Of two cost states that share the prefix ``base``, keep the one whose
    delta over ``base`` moves more total bytes — mutually-exclusive paths
    (if/else arms, except handlers) must never SUM into the region bound."""
    base_total = sum(base.values())
    return dict(a) if sum(a.values()) - base_total >= sum(b.values()) - base_total else dict(b)


@dataclass(frozen=True)
class Ctx:
    """Block context: divergence taint (S104's "which abstract host gets
    here") with provenance, and loop depth (S102's trigger)."""

    divergent: Optional[str] = None  # why, or None
    via_call: bool = False  # the divergence crossed a function boundary
    loop_depth: int = 0

    def taint(self, why: str, via_call: bool) -> "Ctx":
        if self.divergent is not None:
            return self if not via_call or self.via_call else replace(self, via_call=True)
        return replace(self, divergent=why, via_call=via_call)

    def in_loop(self) -> "Ctx":
        return replace(self, loop_depth=self.loop_depth + 1)


@dataclass
class Frame:
    """One function (or module) body under interpretation."""

    module: cg.ModuleInfo
    fninfo: Optional[cg.FunctionInfo]
    env: Dict[str, object] = field(default_factory=dict)
    self_val: Optional[Instance] = None
    rets: List[object] = field(default_factory=list)
    blocking: bool = False
    collective: bool = False
    cost: Dict[str, int] = field(default_factory=dict)

    def add_cost(self, op: str, nbytes: Optional[int]) -> None:
        if nbytes:
            self.cost[op] = self.cost.get(op, 0) + int(nbytes)

    def merge_cost(self, other: Dict[str, int]) -> None:
        for op, b in other.items():
            self.cost[op] = self.cost.get(op, 0) + b

    @property
    def region(self) -> str:
        if self.fninfo is not None:
            return self.fninfo.qualname
        return f"{self.module.path}::<module>"


@dataclass
class Summary:
    """A function's effect summary under one abstract calling context."""

    ret: object = UNKNOWN
    blocking: bool = False
    collective: bool = False
    divergent_ret: bool = False
    cost: Dict[str, int] = field(default_factory=dict)


def _value_key(v) -> object:
    if isinstance(v, AbstractArray):
        return ("A", v.rank, repr(v.split), v.shape, v.dtype, v.pending)
    if isinstance(v, Const):
        try:
            hash(v.value)
            return ("C", v.value)
        except TypeError:
            return ("C", repr(v.value)[:64])
    if isinstance(v, Scalar):
        return ("S", v.divergent, v.via_call)
    if isinstance(v, Instance):
        return ("I", v.cls)
    if isinstance(v, VTuple):
        return ("T",) + tuple(_value_key(i) for i in v.items[:8])
    return "?"


# ----------------------------------------------------------------------
# the heat API op tables
# ----------------------------------------------------------------------
_FACTORIES = {
    "empty", "zeros", "ones", "full", "array", "asarray",
    "empty_like", "zeros_like", "ones_like", "full_like",
    "arange", "linspace", "logspace", "eye",
    # heat_tpu.core.random
    "rand", "randn", "standard_normal", "normal", "random", "uniform",
    "randint", "randperm", "permutation",
}
_UNARY_ELEMENTWISE = {
    "abs", "absolute", "sqrt", "rsqrt", "exp", "exp2", "expm1", "log", "log2",
    "log10", "log1p", "sin", "cos", "tan", "sinh", "cosh", "tanh", "arcsin",
    "arccos", "arctan", "arcsinh", "arccosh", "arctanh", "floor", "ceil",
    "trunc", "round", "rint", "sign", "square", "negative", "positive",
    "reciprocal", "isnan", "isinf", "isfinite", "logical_not", "invert",
    "conjugate", "conj", "real", "imag", "angle", "erf", "erfinv", "sigmoid",
    "clip", "fabs", "modf", "frexp", "nan_to_num", "copy",
}
_BINARY_ELEMENTWISE = {
    "add", "subtract", "sub", "multiply", "mul", "divide", "div",
    "true_divide", "floor_divide", "mod", "remainder", "fmod", "pow",
    "power", "arctan2", "hypot", "minimum", "maximum", "logaddexp",
    "logaddexp2", "logical_and", "logical_or", "logical_xor", "bitwise_and",
    "bitwise_or", "bitwise_xor", "left_shift", "right_shift", "gcd", "lcm",
    "copysign", "nextafter", "equal", "not_equal", "greater",
    "greater_equal", "less", "less_equal", "isclose",
}
_REDUCTIONS = {
    "sum", "prod", "mean", "average", "std", "var", "min", "max", "amin",
    "amax", "argmin", "argmax", "all", "any", "median", "nansum", "nanmean",
    "count_nonzero", "norm",
}
_CUM_OPS = {"cumsum", "cumprod"}
#: array methods that block on the device (host reads of pending chains)
_BLOCKING_METHODS = {"item", "numpy", "tolist", "__float__", "__int__"}
_SYNC_BUILTINS = {"float", "int", "bool", "complex"}


# ----------------------------------------------------------------------
# the analyzer
# ----------------------------------------------------------------------
class Analyzer:
    def __init__(self, graph: cg.CallGraph, mesh_size: int = DEFAULT_MESH_SIZE):
        self.graph = graph
        self.p = max(1, int(mesh_size))
        self.summaries: Dict[tuple, Summary] = {}
        self.context_count: Dict[str, int] = {}
        self.active: set = set()
        self.call_depth = 0
        self.findings: Dict[tuple, Finding] = {}
        #: region qualname -> {"path", "line", "cost": {op: bytes}, "bytes"}
        self.regions: Dict[str, dict] = {}

    # -- findings --------------------------------------------------------
    def emit(self, rule_id: str, node: ast.AST, fr: Frame, message: str) -> None:
        key = (rule_id, fr.module.path, node.lineno, node.col_offset)
        if key in self.findings:
            return
        rule = _RULE_BY_ID[rule_id]
        lines = fr.module.lines
        self.findings[key] = Finding(
            rule=rule_id,
            path=fr.module.path,
            line=node.lineno,
            col=node.col_offset,
            severity=rule.severity,
            message=message,
            hint=rule.hint,
            source=(
                lines[node.lineno - 1].strip()
                if 0 < node.lineno <= len(lines)
                else ""
            ),
        )

    # -- entry points ----------------------------------------------------
    def analyze_module(self, mod: cg.ModuleInfo) -> None:
        fr = Frame(module=mod, fninfo=None)
        self.exec_block(mod.tree.body, fr, Ctx())
        self._record_region(fr, mod.tree)

    def analyze_function(self, fn: cg.FunctionInfo) -> None:
        """Default-context analysis: parameters UNKNOWN (methods get a fresh
        Instance for ``self``), so intra-function hazards surface even when
        no analyzed caller reaches the function."""
        args = []
        node = fn.node
        params = node.args.posonlyargs + node.args.args
        if fn.cls and params and params[0].arg == "self":
            args.append(Instance(fn.cls))
        summary = self.call_function(fn, args, {}, None, None, Ctx())
        rec = self.regions.get(fn.qualname)
        if rec is None or sum(summary.cost.values()) > rec["bytes"]:
            self.regions[fn.qualname] = {
                "path": fn.module.path,
                "line": fn.node.lineno,
                "cost": dict(summary.cost),
                "bytes": sum(summary.cost.values()),
            }

    def _record_region(self, fr: Frame, node) -> None:
        rec = self.regions.get(fr.region)
        total = sum(fr.cost.values())
        if rec is None or total > rec["bytes"]:
            self.regions[fr.region] = {
                "path": fr.module.path,
                "line": getattr(node, "lineno", 1),
                "cost": dict(fr.cost),
                "bytes": total,
            }

    # -- function calls --------------------------------------------------
    def call_function(
        self,
        fn: cg.FunctionInfo,
        args: List[object],
        kwargs: Dict[str, object],
        node: Optional[ast.Call],
        caller: Optional[Frame],
        ctx: Ctx,
    ) -> Summary:
        """Interpret (or recall) ``fn`` under the given abstract arguments,
        then apply the interprocedural rules at the call site."""
        summary = self._summarize(fn, args, kwargs)
        if caller is not None and node is not None:
            caller.blocking |= summary.blocking
            caller.collective |= summary.collective
            caller.merge_cost(summary.cost)
            if ctx.loop_depth and summary.blocking:
                self.emit(
                    "S102",
                    node,
                    caller,
                    f"`{fn.name}` blocks on the device (its summary reaches a "
                    "host read of a pending chain) and is called inside a "
                    "loop: every iteration fences the async-forcing pipeline "
                    "— H002's hazard, hidden behind this call boundary",
                )
            if ctx.divergent is not None and (summary.collective or summary.blocking):
                what = "a collective" if summary.collective else "a forcing point"
                self.emit(
                    "S104",
                    node,
                    caller,
                    f"on the host-divergent path ({ctx.divergent}), one "
                    f"abstract host calls `{fn.name}` — which reaches {what} "
                    "— and the other never does: the hosts that skip this "
                    "call never join, the mesh deadlocks (H001 across the "
                    "function boundary)",
                )
        ret = summary.ret
        if summary.divergent_ret:
            ret = Scalar(divergent=True, via_call=True)
        return replace(summary, ret=ret)

    def _bind_params(
        self, fn: cg.FunctionInfo, args: List[object], kwargs: Dict[str, object]
    ) -> Dict[str, object]:
        node = fn.node
        a = node.args
        env: Dict[str, object] = {}

        def seed(p: ast.arg) -> object:
            # a `x: DNDarray` annotation seeds an array of unknown layout —
            # enough for the effect rules (S102/S104) even when no analyzed
            # caller supplies a concrete lattice state
            if p.annotation is not None and last_name(p.annotation) == "DNDarray":
                return AbstractArray(rank=None, split=TOP)
            return UNKNOWN

        params = [p.arg for p in a.posonlyargs + a.args]
        for i, p in enumerate(a.posonlyargs + a.args):
            env[p.arg] = args[i] if i < len(args) and args[i] is not UNKNOWN else seed(p)
        if a.vararg is not None:
            env[a.vararg.arg] = UNKNOWN
        for p in a.kwonlyargs:
            env[p.arg] = UNKNOWN
        if a.kwarg is not None:
            env[a.kwarg.arg] = UNKNOWN
        # defaults for missing trailing positionals (literals only)
        defaults = a.defaults
        if defaults:
            for i, d in enumerate(defaults):
                name = params[len(params) - len(defaults) + i]
                if env.get(name) is UNKNOWN and isinstance(d, ast.Constant):
                    env[name] = Const(d.value)
        for name, v in kwargs.items():
            if name in env or name in [p.arg for p in a.kwonlyargs]:
                env[name] = v
        return env

    def _summarize(
        self, fn: cg.FunctionInfo, args: List[object], kwargs: Dict[str, object]
    ) -> Summary:
        has_instance = any(isinstance(v, Instance) for v in args) or any(
            isinstance(v, Instance) for v in kwargs.values()
        )
        key = None
        if not has_instance:
            argkey = tuple(_value_key(v) for v in args) + tuple(
                sorted((k, _value_key(v)) for k, v in kwargs.items())
            )
            if self.context_count.get(fn.qualname, 0) >= MAX_CONTEXTS:
                argkey = "ctx-cap"
                args, kwargs = [], {}
            key = (fn.qualname, argkey)
            hit = self.summaries.get(key)
            if hit is not None:
                return hit
        if fn.qualname in self.active or self.call_depth >= MAX_CALL_DEPTH:
            return Summary()  # recursion/depth: conservative, effect-free
        self.active.add(fn.qualname)
        self.call_depth += 1
        try:
            fr = Frame(module=fn.module, fninfo=fn, env=self._bind_params(fn, args, kwargs))
            if args and isinstance(args[0], Instance):
                fr.self_val = args[0]
            self.exec_block(fn.node.body, fr, Ctx())
            ret: object = Const(None)
            if fr.rets:
                ret = fr.rets[0]
                for r in fr.rets[1:]:
                    ret = lat.join(ret, r)
            summary = Summary(
                ret=ret,
                blocking=fr.blocking,
                collective=fr.collective,
                divergent_ret=any(lat.is_divergent(r) for r in fr.rets),
                cost=dict(fr.cost),
            )
        finally:
            self.active.discard(fn.qualname)
            self.call_depth -= 1
        if key is not None:
            self.summaries[key] = summary
            self.context_count[fn.qualname] = self.context_count.get(fn.qualname, 0) + 1
        # the region ledger keeps each function's COSTLIEST analyzed context
        # (budgets bound the worst statically-seen call pattern)
        rec = self.regions.get(fn.qualname)
        total = sum(summary.cost.values())
        if rec is None or total > rec["bytes"]:
            self.regions[fn.qualname] = {
                "path": fn.module.path,
                "line": fn.node.lineno,
                "cost": dict(summary.cost),
                "bytes": total,
            }
        return summary

    def instantiate(
        self,
        ci: cg.ClassInfo,
        args: List[object],
        kwargs: Dict[str, object],
        node: Optional[ast.Call],
        caller: Optional[Frame],
        ctx: Ctx,
    ) -> Instance:
        inst = Instance(ci.name)
        init = self.graph.resolve_method(ci.name, "__init__")
        if init is not None:
            self.call_function(init, [inst] + list(args), kwargs, node, caller, ctx)
        return inst

    # -- statements ------------------------------------------------------
    def exec_block(self, stmts: Sequence[ast.stmt], fr: Frame, ctx: Ctx) -> None:
        for stmt in stmts:
            self.exec_stmt(stmt, fr, ctx)
            if isinstance(stmt, ast.If) and _terminates(stmt.body):
                test_v = self._peek_divergence(stmt.test, fr)
                if test_v is not None:
                    # `if divergent: return` — everything after runs on the
                    # OTHER abstract host only
                    ctx = ctx.taint(f"early exit on line {stmt.lineno}", test_v)

    def _peek_divergence(self, test: ast.AST, fr: Frame) -> Optional[bool]:
        """Whether ``test`` is host-divergent under the current env, without
        re-emitting effects (env lookups + divergent-call syntax only).
        Returns via_call or None."""
        via = None
        for sub in ast.walk(test):
            if isinstance(sub, ast.Call) and _divergent_call(sub):
                via = via or False
            elif isinstance(sub, ast.Name):
                v = fr.env.get(sub.id)
                if lat.is_divergent(v):
                    via = via or bool(getattr(v, "via_call", False))
        return via

    def exec_stmt(self, stmt: ast.stmt, fr: Frame, ctx: Ctx) -> None:
        if isinstance(stmt, ast.Assign):
            v = self.eval_expr(stmt.value, fr, ctx)
            for t in stmt.targets:
                self.bind_target(t, v, fr)
        elif isinstance(stmt, ast.AnnAssign):
            if stmt.value is not None:
                self.bind_target(stmt.target, self.eval_expr(stmt.value, fr, ctx), fr)
        elif isinstance(stmt, ast.AugAssign):
            cur = (
                fr.env.get(stmt.target.id, UNKNOWN)
                if isinstance(stmt.target, ast.Name)
                else UNKNOWN
            )
            v = self.binary_transfer(
                [cur, self.eval_expr(stmt.value, fr, ctx)], stmt, fr, ctx
            )
            self.bind_target(stmt.target, v, fr)
        elif isinstance(stmt, ast.Return):
            v = self.eval_expr(stmt.value, fr, ctx) if stmt.value is not None else Const(None)
            fr.rets.append(v)
        elif isinstance(stmt, ast.Expr):
            self.eval_expr(stmt.value, fr, ctx)
        elif isinstance(stmt, ast.If):
            test_v = self.eval_expr(stmt.test, fr, ctx)
            branch_ctx = ctx
            if lat.is_divergent(test_v):
                branch_ctx = ctx.taint(
                    f"branch on line {stmt.lineno}'s host-divergent test",
                    bool(getattr(test_v, "via_call", False)),
                )
            env_before = dict(fr.env)
            cost_before = dict(fr.cost)
            self.exec_block(stmt.body, fr, branch_ctx)
            env_body, cost_body = fr.env, fr.cost
            fr.env = dict(env_before)
            fr.cost = dict(cost_before)
            self.exec_block(stmt.orelse, fr, branch_ctx)
            fr.env = lat.join_env(env_body, fr.env)
            # the arms are mutually exclusive: the region's bound takes the
            # COSTLIER path, never the sum of both
            fr.cost = _costlier_path(cost_before, cost_body, fr.cost)
        elif isinstance(stmt, (ast.While, ast.For, ast.AsyncFor)):
            self.exec_loop(stmt, fr, ctx)
        elif isinstance(stmt, (ast.With, ast.AsyncWith)):
            for item in stmt.items:
                v = self.eval_expr(item.context_expr, fr, ctx)
                if item.optional_vars is not None:
                    self.bind_target(item.optional_vars, v, fr)
            self.exec_block(stmt.body, fr, ctx)
        elif isinstance(stmt, ast.Try):
            env_before = dict(fr.env)
            self.exec_block(stmt.body, fr, ctx)
            merged = fr.env
            cost_body_only = dict(fr.cost)
            best_cost = cost_body_only
            for handler in stmt.handlers:
                fr.env = lat.join_env(env_before, dict(merged))
                fr.cost = dict(cost_body_only)
                if handler.name:
                    fr.env[handler.name] = UNKNOWN
                self.exec_block(handler.body, fr, ctx)
                merged = lat.join_env(merged, fr.env)
                # exceptional arms are mutually exclusive: keep the
                # costliest single arm, never the sum across handlers
                best_cost = _costlier_path(cost_body_only, best_cost, fr.cost)
            fr.env = merged
            fr.cost = dict(best_cost)
            self.exec_block(stmt.orelse, fr, ctx)
            self.exec_block(stmt.finalbody, fr, ctx)
        elif isinstance(stmt, ast.Raise):
            if stmt.exc is not None:
                self.eval_expr(stmt.exc, fr, ctx)
        elif isinstance(stmt, ast.Assert):
            self.eval_expr(stmt.test, fr, ctx)
        elif isinstance(stmt, ast.Delete):
            for t in stmt.targets:
                if isinstance(t, ast.Name):
                    fr.env.pop(t.id, None)
        elif isinstance(stmt, (ast.FunctionDef, ast.AsyncFunctionDef, ast.ClassDef)):
            fr.env[stmt.name] = UNKNOWN  # nested defs: their own units
        # Import/Pass/Break/Continue/Global/Nonlocal: no dataflow

    def exec_loop(self, stmt, fr: Frame, ctx: Ctx) -> None:
        """Loop bodies re-interpret to a fixpoint under join. Every
        sub-lattice is flat, so join IS the widening: a binding that
        changes across iterations reaches its top (split → ⊤, dim →
        unknown, kind → UNKNOWN) after one join and the state stabilizes
        within two or three passes (MAX_LOOP_ITERS is the hard cap)."""
        loop_ctx = ctx.in_loop()
        iter_elem = None
        if not isinstance(stmt, ast.While):
            # the iterable expression evaluates ONCE at runtime, outside the
            # iteration context
            iter_v = self.eval_expr(stmt.iter, fr, ctx)
            iter_elem = self._iter_element(iter_v)
        pre = dict(fr.env)
        cost_entry = dict(fr.cost)
        for i in range(MAX_LOOP_ITERS):
            fr.env = dict(pre)
            # the cost model prices ONE interpretation of the body: fixpoint
            # re-runs must not multiply the region bound
            fr.cost = dict(cost_entry)
            body_ctx = loop_ctx
            if isinstance(stmt, ast.While):
                # the test re-evaluates every iteration — a blocking helper
                # in it is exactly the per-iteration fence (H002 counts
                # While tests; so does S102)
                test_v = self.eval_expr(stmt.test, fr, loop_ctx)
                if lat.is_divergent(test_v):
                    body_ctx = loop_ctx.taint(
                        f"while-test on line {stmt.lineno} is host-divergent",
                        bool(getattr(test_v, "via_call", False)),
                    )
            if iter_elem is not None:
                self.bind_target(stmt.target, iter_elem, fr)
            self.exec_block(stmt.body, fr, body_ctx)
            post = fr.env
            new: Dict[str, object] = {}
            for name in set(pre) | set(post):
                if name in pre and name in post:
                    new[name] = lat.join(pre[name], post[name])
                else:
                    new[name] = post.get(name, pre.get(name))
            if new == pre:
                break
            pre = new
        fr.env = pre
        self.exec_block(stmt.orelse, fr, ctx)

    @staticmethod
    def _iter_element(v) -> object:
        if isinstance(v, VTuple):
            if not v.items:
                return UNKNOWN
            elem = v.items[0]
            for i in v.items[1:]:
                elem = lat.join(elem, i)
            return elem
        if isinstance(v, Const) and isinstance(v.value, (tuple, list)):
            vals = [Const(x) for x in v.value]
            return Analyzer._iter_element(VTuple(tuple(vals)))
        if isinstance(v, AbstractArray):
            if v.rank is not None and v.rank > 1:
                return AbstractArray(rank=v.rank - 1, split=TOP, pending=v.pending)
            return UNKNOWN
        return UNKNOWN

    def bind_target(self, target: ast.AST, value, fr: Frame) -> None:
        if isinstance(target, ast.Name):
            fr.env[target.id] = value
        elif isinstance(target, (ast.Tuple, ast.List)):
            items = None
            if isinstance(value, VTuple) and len(value.items) == len(target.elts):
                items = value.items
            for i, elt in enumerate(target.elts):
                self.bind_target(elt, items[i] if items else UNKNOWN, fr)
        elif isinstance(target, ast.Starred):
            self.bind_target(target.value, UNKNOWN, fr)
        elif isinstance(target, ast.Attribute):
            obj = self.eval_expr(target.value, fr, Ctx())
            if isinstance(obj, Instance):
                prev = obj.attrs.get(target.attr)
                obj.attrs[target.attr] = (
                    value if prev is None else lat.join(prev, value)
                )
        # Subscript targets: no tracked store

    # -- expressions -----------------------------------------------------
    def eval_expr(self, node: ast.AST, fr: Frame, ctx: Ctx):
        if node is None:
            return UNKNOWN
        if isinstance(node, ast.Constant):
            return Const(node.value)
        if isinstance(node, ast.Name):
            if node.id in fr.env:
                return fr.env[node.id]
            if node.id == "self" and fr.self_val is not None:
                return fr.self_val
            return UNKNOWN
        if isinstance(node, (ast.Tuple, ast.List)):
            return VTuple(tuple(self.eval_expr(e, fr, ctx) for e in node.elts))
        if isinstance(node, ast.BinOp):
            left = self.eval_expr(node.left, fr, ctx)
            right = self.eval_expr(node.right, fr, ctx)
            if isinstance(node.op, ast.MatMult):
                return self.matmul_transfer([left, right], node, fr, ctx)
            return self.binary_transfer([left, right], node, fr, ctx)
        if isinstance(node, ast.UnaryOp):
            v = self.eval_expr(node.operand, fr, ctx)
            if isinstance(v, AbstractArray):
                return v.with_(pending=True)
            if isinstance(v, Const) and isinstance(node.op, ast.USub) and isinstance(
                v.value, (int, float)
            ):
                return Const(-v.value)
            if lat.is_divergent(v):
                return Scalar(divergent=True, via_call=getattr(v, "via_call", False))
            return Scalar() if isinstance(v, (Const, Scalar)) else UNKNOWN
        if isinstance(node, ast.BoolOp):
            vals = [self.eval_expr(v, fr, ctx) for v in node.values]
            if any(lat.is_divergent(v) for v in vals):
                return Scalar(
                    divergent=True,
                    via_call=any(getattr(v, "via_call", False) for v in vals),
                )
            return Scalar()
        if isinstance(node, ast.Compare):
            vals = [self.eval_expr(node.left, fr, ctx)] + [
                self.eval_expr(c, fr, ctx) for c in node.comparators
            ]
            if len(vals) == 2 and any(isinstance(v, AbstractArray) for v in vals):
                return self.binary_transfer(vals, node, fr, ctx)
            if any(lat.is_divergent(v) for v in vals):
                return Scalar(
                    divergent=True,
                    via_call=any(getattr(v, "via_call", False) for v in vals),
                )
            return Scalar()
        if isinstance(node, ast.Call):
            return self.eval_call(node, fr, ctx)
        if isinstance(node, ast.Attribute):
            return self.eval_attribute(node, fr, ctx)
        if isinstance(node, ast.Subscript):
            base = self.eval_expr(node.value, fr, ctx)
            idx = self.eval_expr(node.slice, fr, ctx)
            if isinstance(base, VTuple):
                i = _const_int(idx)
                if i is not None and -len(base.items) <= i < len(base.items):
                    return base.items[i]
                return UNKNOWN
            if isinstance(base, Const) and isinstance(base.value, (tuple, list)):
                i = _const_int(idx)
                if i is not None and -len(base.value) <= i < len(base.value):
                    return Const(base.value[i])
                return UNKNOWN
            if isinstance(base, AbstractArray):
                # indexing reads (and therefore forces) the payload; the
                # sliced layout is not tracked
                return AbstractArray(rank=None, split=TOP, pending=base.pending)
            return UNKNOWN
        if isinstance(node, ast.IfExp):
            self.eval_expr(node.test, fr, ctx)
            return lat.join(
                self.eval_expr(node.body, fr, ctx), self.eval_expr(node.orelse, fr, ctx)
            )
        if isinstance(node, ast.NamedExpr):
            v = self.eval_expr(node.value, fr, ctx)
            self.bind_target(node.target, v, fr)
            return v
        if isinstance(node, ast.Starred):
            return self.eval_expr(node.value, fr, ctx)
        if isinstance(node, ast.JoinedStr):
            for v in node.values:
                if isinstance(v, ast.FormattedValue):
                    self.eval_expr(v.value, fr, ctx)
            return Scalar()
        if isinstance(node, (ast.ListComp, ast.SetComp, ast.GeneratorExp, ast.DictComp)):
            child = dict(fr.env)
            try:
                for gen in node.generators:
                    self.eval_expr(gen.iter, fr, ctx)
                    self.bind_target(gen.target, UNKNOWN, fr)
                if isinstance(node, ast.DictComp):
                    self.eval_expr(node.key, fr, ctx)
                    self.eval_expr(node.value, fr, ctx)
                else:
                    self.eval_expr(node.elt, fr, ctx)
            finally:
                fr.env = child
            return UNKNOWN
        if isinstance(node, ast.Lambda):
            return UNKNOWN
        if isinstance(node, ast.Dict):
            for k, v in zip(node.keys, node.values):
                if k is not None:
                    self.eval_expr(k, fr, ctx)
                self.eval_expr(v, fr, ctx)
            return UNKNOWN
        if isinstance(node, ast.Slice):
            return UNKNOWN
        return UNKNOWN

    # -- attributes ------------------------------------------------------
    def eval_attribute(self, node: ast.Attribute, fr: Frame, ctx: Ctx):
        v = self.eval_expr(node.value, fr, ctx)
        attr = node.attr
        if isinstance(v, AbstractArray):
            if attr == "T":
                return self._transpose(v)
            if attr == "shape":
                return Const(v.shape) if v.shape is not None and all(
                    d is not None for d in v.shape
                ) else UNKNOWN
            if attr == "split":
                if v.split is TOP:
                    return UNKNOWN
                return Const(v.split)
            if attr == "ndim":
                return Const(v.rank) if v.rank is not None else UNKNOWN
            if attr in ("larray", "parray"):
                # payload access forces the chain (dispatch); under a
                # divergence that crossed a function boundary this is the
                # hazard H001 cannot see
                if ctx.divergent is not None and ctx.via_call:
                    self.emit(
                        "S104",
                        node,
                        fr,
                        f"`.{attr}` forces (and dispatches a possibly "
                        f"collective-bearing program) on a path divergent "
                        f"through a callee's return value ({ctx.divergent}) "
                        "— only some hosts dispatch: mesh deadlock",
                    )
                return v.with_(pending=False)
            if attr in ("comm", "device", "dtype"):
                return Scalar()
            return UNKNOWN
        if isinstance(v, Instance):
            return v.attrs.get(attr, UNKNOWN)
        if isinstance(v, Scalar) and v.divergent:
            return Scalar(divergent=True, via_call=v.via_call)
        return UNKNOWN

    @staticmethod
    def _transpose(v: AbstractArray) -> AbstractArray:
        if v.rank == 2:
            split = v.split
            if isinstance(split, int):
                split = 1 - split
            shape = tuple(reversed(v.shape)) if v.shape is not None else None
            return v.with_(split=split, shape=shape, pending=True)
        return AbstractArray(rank=v.rank, split=TOP, dtype=v.dtype)

    # -- calls -----------------------------------------------------------
    def eval_call(self, node: ast.Call, fr: Frame, ctx: Ctx):
        args = [self.eval_expr(a, fr, ctx) for a in node.args if not isinstance(a, ast.Starred)]
        for a in node.args:
            if isinstance(a, ast.Starred):
                self.eval_expr(a.value, fr, ctx)
        kwargs: Dict[str, object] = {}
        for kw in node.keywords:
            v = self.eval_expr(kw.value, fr, ctx)
            if kw.arg is not None:
                kwargs[kw.arg] = v
        func = node.func

        # host-divergent sources (process identity, wall clock, unseeded RNG)
        if _divergent_call(node):
            return Scalar(divergent=True)

        # builtins: blocking casts, print, structural helpers
        if isinstance(func, ast.Name):
            name = func.id
            if name in _SYNC_BUILTINS:
                if any(isinstance(a, AbstractArray) for a in args):
                    self._blocking(node, fr, ctx, f"`{name}()` host read")
                return Scalar()
            if name == "print":
                if any(isinstance(a, AbstractArray) for a in args):
                    self._blocking(node, fr, ctx, "`print` host read")
                return Const(None)
            if name == "len":
                if args and isinstance(args[0], VTuple):
                    return Const(len(args[0].items))
                if args and isinstance(args[0], Const) and isinstance(
                    args[0].value, (tuple, list, str)
                ):
                    return Const(len(args[0].value))
                return Scalar()
            if name in ("range", "enumerate", "zip", "sorted", "reversed", "list", "tuple"):
                return UNKNOWN
            if name in ("abs", "min", "max", "sum") and args and isinstance(
                args[0], AbstractArray
            ):
                # the numpy-protocol builtins force a host read on heat arrays
                self._blocking(node, fr, ctx, f"`{name}()` host read")
                return Scalar()
            target = self.graph.resolve_name(fr.module, name)
            if isinstance(target, cg.FunctionInfo):
                return self.call_function(target, args, kwargs, node, fr, ctx).ret
            if isinstance(target, cg.ClassInfo):
                return self.instantiate(target, args, kwargs, node, fr, ctx)
            return UNKNOWN

        if not isinstance(func, ast.Attribute):
            return UNKNOWN

        # heat-alias-dotted calls: `ht.mean(...)`, `ht.linalg.qr(...)`
        dotted = dotted_name(func)
        root = dotted.split(".")[0] if dotted else ""
        src = fr.module.imports.get(root)
        if src is not None and src.split(".")[0] == "heat_tpu":
            api_tail = dotted[len(root) + 1:]  # "linalg.qr" / "mean"
            result = self.heat_api(api_tail, args, kwargs, node, fr, ctx)
            if result is not NotImplemented:
                return result
            # not in the op table: try the analyzed source (estimator
            # classes, dataset helpers, example mains)
            full = src + ("." + api_tail if api_tail else "")
            target = self.graph.resolve_dotted(full)
            if isinstance(target, cg.FunctionInfo):
                return self.call_function(target, args, kwargs, node, fr, ctx).ret
            if isinstance(target, cg.ClassInfo):
                return self.instantiate(target, args, kwargs, node, fr, ctx)
            return UNKNOWN

        # receiver-value dispatch
        recv = self.eval_expr(func.value, fr, ctx)
        if isinstance(recv, AbstractArray):
            return self.array_method(recv, func, args, kwargs, node, fr, ctx)
        if isinstance(recv, Instance):
            target = self.graph.resolve_method(recv.cls, func.attr)
            if target is not None:
                return self.call_function(
                    target, [recv] + args, kwargs, node, fr, ctx
                ).ret
            return UNKNOWN

        # syntactic collectives on unknown receivers (comm.allreduce(...))
        if _is_collective_call(node):
            fr.collective = True
            nbytes = None
            for a in args:
                nbytes = lat.logical_bytes(a) if isinstance(a, AbstractArray) else nbytes
                if nbytes:
                    break
            op = last_name(func)
            fr.add_cost(op if op else "collective", nbytes)
            if ctx.divergent is not None and ctx.via_call:
                self.emit(
                    "S104",
                    node,
                    fr,
                    f"collective `{dotted or op}` runs on a path divergent "
                    f"through a callee's return value ({ctx.divergent}): "
                    "hosts that skip this branch never join — mesh deadlock "
                    "(H001 cannot see divergence born in a callee)",
                )
            return UNKNOWN
        if func.attr in ("item", "numpy"):
            # syntactic parity with H001's forcing-method detection: even on
            # an untracked receiver, a force under divergence that crossed a
            # function boundary is the hazard the lint cannot see (blocking
            # is NOT recorded here — S102 stays value-based, like H002's
            # heat-taint requirement)
            if ctx.divergent is not None and ctx.via_call:
                self.emit(
                    "S104",
                    node,
                    fr,
                    f"`.{func.attr}()` forces (and dispatches a possibly "
                    f"collective-bearing program) on a path divergent "
                    f"through a callee's return value ({ctx.divergent}) — "
                    "only some hosts dispatch: mesh deadlock",
                )
            return UNKNOWN
        # module-dotted call into another analyzed (non-heat) module:
        # `import helpers; helpers.step(x)`
        if src is not None and isinstance(func.value, ast.Name):
            target = self.graph.resolve_dotted(f"{src}.{func.attr}")
            if isinstance(target, cg.FunctionInfo):
                return self.call_function(target, args, kwargs, node, fr, ctx).ret
            if isinstance(target, cg.ClassInfo):
                return self.instantiate(target, args, kwargs, node, fr, ctx)
        return UNKNOWN

    def _blocking(self, node: ast.AST, fr: Frame, ctx: Ctx, what: str) -> None:
        fr.blocking = True
        if ctx.divergent is not None and ctx.via_call:
            self.emit(
                "S104",
                node,
                fr,
                f"{what} forces (and dispatches a possibly collective-"
                f"bearing program) on a path divergent through a callee's "
                f"return value ({ctx.divergent}) — a multihost deadlock "
                "hazard H001 cannot see",
            )

    # -- the heat API op table ------------------------------------------
    def heat_api(self, api: str, args, kwargs, node, fr: Frame, ctx: Ctx):
        """Transfer functions for the recognized public API (keyed on the
        trailing name). Returns NotImplemented for names the table does not
        model so the caller can fall back to analyzed-source resolution."""
        name = api.split(".")[-1] if api else ""
        if name in _FACTORIES:
            return self.factory_transfer(name, args, kwargs, node)
        if name in _UNARY_ELEMENTWISE:
            if args and isinstance(args[0], AbstractArray):
                return args[0].with_(pending=True)
            return UNKNOWN
        if name in _BINARY_ELEMENTWISE:
            if len(args) >= 2:
                out = kwargs.get("out")
                res = self.binary_transfer(args[:2], node, fr, ctx)
                if isinstance(out, AbstractArray) and isinstance(res, AbstractArray):
                    self._check_out(res, out, node, fr)
                return res
            return UNKNOWN
        if name == "where":
            if len(args) >= 3:
                return self.binary_transfer(args[:3], node, fr, ctx, opname="where")
            return UNKNOWN
        if name in _REDUCTIONS:
            if args and isinstance(args[0], AbstractArray):
                return self.reduce_transfer(args[0], args[1:], kwargs, node, fr)
            return UNKNOWN
        if name in _CUM_OPS:
            if args and isinstance(args[0], AbstractArray):
                return args[0].with_(pending=True)
            return UNKNOWN
        if name == "resplit":
            if args and isinstance(args[0], AbstractArray):
                axis = args[1] if len(args) > 1 else kwargs.get("axis", Const(None))
                return self.resplit_transfer(args[0], axis, node, fr, inplace=False)
            return UNKNOWN
        if name == "reshape":
            if args and isinstance(args[0], AbstractArray):
                shape = _const_shape(args[1]) if len(args) == 2 else _const_shape(
                    VTuple(tuple(args[1:]))
                )
                new_split = _split_arg(
                    kwargs.get("new_split"), "new_split" in kwargs
                )
                rank = len(shape) if shape else None
                return AbstractArray(
                    rank=rank,
                    split=_norm_split(new_split, rank) if "new_split" in kwargs else TOP,
                    shape=shape,
                    dtype=args[0].dtype,
                )
            return UNKNOWN
        if name == "transpose":
            if args and isinstance(args[0], AbstractArray):
                return self._transpose(args[0])
            return UNKNOWN
        if name in ("concatenate", "vstack", "hstack", "stack", "column_stack"):
            splits = []
            if args and isinstance(args[0], VTuple):
                for item in args[0].items:
                    if isinstance(item, AbstractArray):
                        splits.append(item.split)
            split = splits[0] if splits and all(s == splits[0] for s in splits) else TOP
            return AbstractArray(rank=None, split=split)
        if name in ("flatten", "ravel"):
            return AbstractArray(rank=1, split=TOP)
        if name in ("squeeze", "expand_dims", "atleast_2d", "broadcast_to", "tile", "repeat"):
            return AbstractArray(rank=None, split=TOP)
        if name == "astype":
            if args and isinstance(args[0], AbstractArray):
                return args[0].with_(
                    dtype=_dtype_from_node(node.args[1] if len(node.args) > 1 else None)
                    or args[0].dtype
                )
            return UNKNOWN
        if name == "qr":
            return self.qr_transfer(args, kwargs, node, fr)
        if name == "solve_triangular":
            return self.solve_triangular_transfer(args, kwargs, node, fr)
        if name in ("matmul", "dot"):
            return self.matmul_transfer(args, node, fr, ctx)
        if name == "svd":
            a = lat.as_array(args[0]) if args else None
            if a is None:
                return UNKNOWN
            # svd.py split semantics (reduced form): split-0 -> split-0 U,
            # replicated S/Vh; split-1 -> the mirror image
            if a.split is TOP:
                u_s, s_s, v_s = TOP, TOP, TOP
            elif a.split == 1:
                u_s, s_s, v_s = None, None, 1
            else:
                u_s, s_s, v_s = a.split, None, None
            dt = _promote(a.dtype, "float32")
            k = None
            if a.shape is not None and all(d is not None for d in a.shape):
                k = min(a.shape)
            u = AbstractArray(
                rank=2, split=u_s, dtype=dt,
                shape=(a.shape[0], k) if a.shape is not None and k else None,
            )
            s = AbstractArray(rank=1, split=s_s, dtype=dt, shape=(k,) if k else None)
            vh = AbstractArray(
                rank=2, split=v_s, dtype=dt,
                shape=(k, a.shape[1]) if a.shape is not None and k else None,
            )
            compute_uv = kwargs.get("compute_uv")
            if isinstance(compute_uv, Const) and compute_uv.value is False:
                return s
            return VTuple((u, s, vh))
        if name in ("cholesky", "inv", "lu", "solve", "lstsq", "det", "cg", "lanczos"):
            return AbstractArray(rank=None, split=TOP)
        if name in ("get_comm", "get_device", "seed", "save", "load"):
            return Scalar()
        return NotImplemented

    def _check_out(self, res: AbstractArray, out: AbstractArray, node, fr: Frame) -> None:
        if (
            isinstance(res.split, int)
            and isinstance(out.split, int)
            and res.split != out.split
        ):
            nbytes = lat.logical_bytes(res)
            fr.add_cost("reshard.implicit", nbytes)
            self.emit(
                "S101",
                node,
                fr,
                f"`out=` buffer is split={out.split} but the result's "
                f"dominant split is {res.split}: the store reshards "
                f"implicitly ({self._fmt_bytes(nbytes)} moved with no fault "
                "site, telemetry bytes, or fusion node)",
            )

    @staticmethod
    def _fmt_bytes(nbytes: Optional[int]) -> str:
        if not nbytes:
            return "unknown bytes"
        return f"~{int(nbytes)} B estimated"

    def factory_transfer(self, name: str, args, kwargs, node: ast.Call):
        split_present = "split" in kwargs
        split = _split_arg(kwargs.get("split"), split_present)
        dtype = None
        for kw in node.keywords:
            if kw.arg == "dtype":
                dtype = _dtype_from_node(kw.value)
        shape: Optional[Tuple[int, ...]] = None
        if name.endswith("_like"):
            base = lat.as_array(args[0]) if args else None
            if base is not None:
                shape = base.shape if base.shape and all(
                    d is not None for d in base.shape
                ) else None
                if not split_present:
                    split = base.split
                dtype = dtype or base.dtype
        elif name in ("rand", "randn"):
            dims = [_const_int(a) for a in args]
            if dims and all(d is not None for d in dims):
                shape = tuple(dims)
            dtype = dtype or "float32"
        elif name in ("standard_normal", "normal", "random", "uniform"):
            sv = kwargs.get("shape") or kwargs.get("size")
            if sv is None and args:
                sv = args[-1] if name in ("normal", "uniform") else args[0]
            shape = _const_shape(sv) if sv is not None else None
            dtype = dtype or "float32"
        elif name == "randint":
            sv = kwargs.get("size")
            shape = _const_shape(sv) if sv is not None else None
            dtype = dtype or "int64"
        elif name in ("randperm", "permutation"):
            n = _const_int(args[0]) if args else None
            shape = (n,) if n is not None else None
            dtype = dtype or "int64"
        elif name == "arange":
            vals = [_const_int(a) for a in args]
            if vals and all(v is not None for v in vals):
                if len(vals) == 1:
                    n = max(0, vals[0])
                elif len(vals) == 2:
                    n = max(0, vals[1] - vals[0])
                else:
                    step = vals[2] or 1
                    n = max(0, _ceil_div(vals[1] - vals[0], step))
                shape = (n,)
            dtype = dtype or "int64"
        elif name in ("linspace", "logspace"):
            n = _const_int(kwargs.get("num")) if "num" in kwargs else (
                _const_int(args[2]) if len(args) > 2 else 50
            )
            shape = (n,) if isinstance(n, int) else None
            dtype = dtype or "float32"
        elif name == "eye":
            s = _const_shape(args[0]) if args else None
            if s is not None:
                shape = (s[0], s[0]) if len(s) == 1 else (s[0], s[1])
            dtype = dtype or "float32"
        elif name in ("array", "asarray"):
            base = lat.as_array(args[0]) if args else None
            if base is not None:
                shape = base.shape if base.shape and all(
                    d is not None for d in base.shape
                ) else None
            elif args and isinstance(args[0], (Const, VTuple)):
                shape = _const_shape(args[0])
            dtype = dtype or (base.dtype if base is not None else None)
        elif name == "full":
            shape = _const_shape(args[0]) if args else None
            dtype = dtype or "float32"
        else:  # empty/zeros/ones
            shape = _const_shape(args[0]) if args else None
            dtype = dtype or "float32"
        rank = len(shape) if shape is not None else None
        return AbstractArray(
            rank=rank,
            split=_norm_split(split, rank),
            shape=shape,
            dtype=dtype,
            pending=True,
            device="mesh",
        )

    # -- the split-dominance transfer (S101 lives here) ------------------
    def binary_transfer(self, ops, node, fr: Frame, ctx: Ctx, opname: str = "") -> object:
        arrays = [v for v in ops if isinstance(v, AbstractArray)]
        if not arrays:
            # constant folding for shape arithmetic; divergence propagates
            if all(isinstance(v, Const) for v in ops) and isinstance(node, ast.BinOp):
                try:
                    l, r = ops[0].value, ops[1].value
                    op = node.op
                    if isinstance(op, ast.Add):
                        return Const(l + r)
                    if isinstance(op, ast.Sub):
                        return Const(l - r)
                    if isinstance(op, ast.Mult):
                        return Const(l * r)
                    if isinstance(op, ast.FloorDiv):
                        return Const(l // r)
                    if isinstance(op, ast.Mod):
                        return Const(l % r)
                    if isinstance(op, ast.Pow):
                        return Const(l ** r)
                    if isinstance(op, ast.Div):
                        return Const(l / r)
                except Exception:
                    return Scalar()
            if any(lat.is_divergent(v) for v in ops):
                return Scalar(
                    divergent=True,
                    via_call=any(getattr(v, "via_call", False) for v in ops),
                )
            return Scalar() if all(isinstance(v, (Const, Scalar)) for v in ops) else UNKNOWN

        # output rank/shape from broadcasting
        shapes = [a.shape for a in arrays]
        out_shape = shapes[0]
        for s in shapes[1:]:
            out_shape = lat.bcast_shape(out_shape, s)
        ranks = [a.rank for a in arrays]
        out_rank = None
        if all(r is not None for r in ranks):
            out_rank = max(ranks)
        if out_shape is not None:
            out_rank = len(out_shape)

        def adjusted(a: AbstractArray) -> lat.Split:
            s = _norm_split(a.split, a.rank)
            if not isinstance(s, int):
                return s
            if a.rank is None or out_rank is None:
                return TOP
            return s + (out_rank - a.rank)

        adj = [adjusted(a) for a in arrays]

        # S101: two operands with concrete-but-different distribution axes
        concrete = [
            (a, s) for a, s in zip(arrays, adj) if isinstance(s, int)
        ]
        if len(concrete) >= 2:
            dom_arr, dom_split = concrete[0]
            for other_arr, other_split in concrete[1:]:
                if other_split != dom_split:
                    nbytes = lat.logical_bytes(other_arr)
                    fr.add_cost("reshard.implicit", nbytes)
                    what = f"`{opname}`" if opname else "this operation"
                    self.emit(
                        "S101",
                        node,
                        fr,
                        f"operands meet at {what} with different concrete "
                        f"splits ({dom_split} vs {other_split}): split "
                        f"dominance keeps split={dom_split} and the other "
                        f"side is resharded implicitly, invisible in the "
                        f"source ({self._fmt_bytes(nbytes)} on the wire, "
                        "every call) — make the layout decision explicit "
                        "where it is made",
                    )
                    break

        # split dominance for the result (first operand wins if set)
        out_split: lat.Split = None
        for s in adj:
            if s is TOP:
                out_split = TOP
                break
            if s is not None:
                out_split = s
                break
        dtype = arrays[0].dtype
        for a in arrays[1:]:
            dtype = _promote(dtype, a.dtype)
        if out_split is not None and out_split is not TOP and out_rank is not None:
            if not (0 <= out_split < out_rank):
                out_split = None
        return AbstractArray(
            rank=out_rank,
            split=out_split,
            shape=out_shape,
            dtype=dtype,
            pending=True,
            device="mesh",
        )

    def matmul_transfer(self, ops, node, fr: Frame, ctx: Ctx):
        arrays = [v for v in ops if isinstance(v, AbstractArray)]
        if not arrays:
            return UNKNOWN
        if len(arrays) < 2 or not all(a.rank == 2 for a in arrays):
            return AbstractArray(rank=None, split=TOP)
        a, b = arrays[0], arrays[1]
        # linalg/basics.py matmul case table: a row-split left operand yields
        # a row-split product, a column-split right operand a column-split
        # product; contraction-axis splits psum
        if a.split is TOP or b.split is TOP:
            split: lat.Split = TOP
        elif a.split == 0:
            split = 0
        elif b.split == 1:
            split = 1
        else:
            split = None
        shape = None
        if a.shape is not None and b.shape is not None:
            shape = (a.shape[0], b.shape[1])
        dtype = _promote(a.dtype, b.dtype)
        out = AbstractArray(rank=2, split=split, shape=shape, dtype=dtype)
        if (a.split == 1 or b.split == 0) and self.p > 1:
            # contraction-axis split: the partial products psum (the case
            # table's reduce combos) — lower-bounded at the result bytes
            fr.add_cost("reduce.psum", lat.logical_bytes(out) or 0)
        return out

    def reduce_transfer(self, x: AbstractArray, rest, kwargs, node, fr: Frame):
        axis_v = kwargs.get("axis", rest[0] if rest else Const(None))
        keepdims = kwargs.get("keepdims", Const(False))
        keep = isinstance(keepdims, Const) and bool(keepdims.value)
        axes: Optional[Tuple[int, ...]] = None
        if isinstance(axis_v, Const):
            if axis_v.value is None:
                axes = None
            elif isinstance(axis_v.value, int):
                axes = (axis_v.value,)
            elif isinstance(axis_v.value, (tuple, list)):
                axes = tuple(axis_v.value)
            else:
                return AbstractArray(rank=None, split=TOP, dtype=x.dtype)
        elif isinstance(axis_v, VTuple):
            dims = [_const_int(i) for i in axis_v.items]
            if all(d is not None for d in dims):
                axes = tuple(dims)
            else:
                return AbstractArray(rank=None, split=TOP, dtype=x.dtype)
        else:
            return AbstractArray(rank=None, split=TOP, dtype=x.dtype)
        if axes is not None and x.rank is not None:
            axes = tuple(a % x.rank for a in axes)
        split = x.split
        crosses = False
        if split is None:
            out_split: lat.Split = None
        elif axes is None:
            out_split = None
            crosses = isinstance(split, int) or split is TOP
        elif split is TOP:
            out_split = TOP
            crosses = True  # may cross: cost as a lower bound stays 0
        elif split in axes:
            out_split = None
            crosses = True
        elif keep:
            out_split = split
        else:
            out_split = split - sum(1 for a in axes if a < split)
        # shape bookkeeping
        shape = None
        if x.shape is not None and x.rank is not None:
            if axes is None:
                shape = (1,) * x.rank if keep else ()
            else:
                dims = list(x.shape)
                for a in sorted(set(axes), reverse=True):
                    if keep:
                        dims[a] = 1
                    else:
                        del dims[a]
                shape = tuple(dims)
        rank = len(shape) if shape is not None else None
        out = AbstractArray(
            rank=rank, split=out_split, shape=shape, dtype=x.dtype, pending=True
        )
        if crosses and isinstance(x.split, int) and self.p > 1:
            # a split-crossing reduction psums its RESULT inside the fused
            # program — the lower bound the cost model prices
            fr.add_cost("reduce.psum", lat.logical_bytes(out) or 0)
        return out

    def resplit_transfer(
        self, x: AbstractArray, axis_v, node, fr: Frame, inplace: bool
    ) -> AbstractArray:
        axis: lat.Split
        if isinstance(axis_v, Const):
            axis = axis_v.value if axis_v.value is None or isinstance(axis_v.value, int) else TOP
        else:
            axis = TOP
        axis = _norm_split(axis, x.rank)
        x = x.with_(split=_norm_split(x.split, x.rank))
        if axis is None and isinstance(x.split, int):
            nbytes = lat.logical_bytes(x)
            fr.add_cost("reshard", nbytes)
            fr.collective = True
            self.emit(
                "S103",
                node,
                fr,
                f"resplit to None of a value inferred split={x.split}: the "
                f"result is replicated ({self._fmt_bytes(nbytes)} allgathered, "
                "O(n) per-host memory) on a path where the sharded layout "
                "was available",
            )
        elif isinstance(axis, int) and isinstance(x.split, int) and axis != x.split:
            fr.add_cost("reshard", lat.logical_bytes(x))
            fr.collective = True
        elif axis is TOP and isinstance(x.split, int):
            fr.collective = True
        return x.with_(split=axis, pending=True)

    # -- declared linalg schedules (mirrors of the runtime's formulas) ---
    def qr_transfer(self, args, kwargs, node, fr: Frame):
        a = lat.as_array(args[0]) if args else None
        method = kwargs.get("method", Const("auto"))
        method = method.value if isinstance(method, Const) else "auto"
        q_split = a.split if a is not None else TOP
        r_split: lat.Split = None
        if (
            a is not None
            and a.shape is not None
            and len(a.shape) == 2
            and all(d is not None for d in a.shape)
            and isinstance(a.split, (int, type(None)))
        ):
            m, n = a.shape
            p = self.p
            item = lat.itemsize(a.dtype)
            acc = lat.itemsize(_promote(a.dtype, "float32"))
            # routing mirror of core/linalg/qr.py::qr
            took_cholqr2 = False
            if method in ("auto", "cholqr2") and (
                method == "cholqr2"
                or (m >= 2 * n and n * n <= (1 << 22) and a.split != 1)
            ):
                if a.split == 0 and p > 1:
                    # CholeskyQR2: two passes psum one (n, n) Gram partial
                    fr.add_cost("allreduce", 2 * n * n * acc)
                    fr.collective = True
                took_cholqr2 = True
            if not took_cholqr2:
                if a.split == 0 and p > 1 and m >= n and _ceil_div(m, p) >= n:
                    # TSQR: one all_gather of the p (k1, n) R factors
                    k1 = min(_ceil_div(m, p), n)
                    fr.add_cost("allgather", p * k1 * n * item)
                    fr.collective = True
                elif a.split == 1 and p > 1 and m >= n:
                    # panel loop: per panel one (m, c) Q bcast + (c, c) R
                    c = n // p
                    if c:
                        fr.add_cost("bcast", p * (m * c + c * c) * item)
                        fr.collective = True
                    r_split = 1
        elif a is not None and a.split == 1:
            r_split = 1
        q = AbstractArray(
            rank=2,
            split=q_split,
            shape=a.shape if a is not None else None,
            dtype=_promote(a.dtype if a is not None else None, "float32"),
        )
        r = AbstractArray(rank=2, split=r_split, dtype=q.dtype)
        return VTuple((q, r))

    def solve_triangular_transfer(self, args, kwargs, node, fr: Frame):
        A = lat.as_array(args[0]) if args else None
        b = lat.as_array(args[1]) if len(args) > 1 else None
        out_rank = b.rank if b is not None else None
        if (
            A is not None
            and isinstance(A.split, int)
            and self.p > 1
            and A.shape is not None
            and all(d is not None for d in A.shape)
        ):
            n = A.shape[0]
            p = self.p
            rows_loc = _ceil_div(n, p)
            n_stages = min(p, n)
            k = 1
            if b is not None and b.rank == 2 and b.shape is not None and b.shape[1]:
                k = b.shape[1]
            acc = lat.itemsize(_promote(_promote(A.dtype, b.dtype if b else None), "float32"))
            # one psum of one solved (rows_loc, k) block per stage
            fr.add_cost("allreduce", n_stages * rows_loc * k * acc)
            fr.collective = True
        return AbstractArray(rank=out_rank, split=b.split if b is not None else TOP)

    # -- array methods ---------------------------------------------------
    def array_method(
        self, recv: AbstractArray, func: ast.Attribute, args, kwargs, node, fr: Frame, ctx: Ctx
    ):
        name = func.attr
        if name in _BLOCKING_METHODS:
            self._blocking(node, fr, ctx, f"`.{name}()` host read")
            if isinstance(func.value, ast.Name):
                fr.env[func.value.id] = recv.with_(pending=False)
            return Scalar()
        if name in _REDUCTIONS:
            return self.reduce_transfer(recv, args, kwargs, node, fr)
        if name in _CUM_OPS:
            return recv.with_(pending=True)
        if name in _UNARY_ELEMENTWISE:
            return recv.with_(pending=True)
        if name in _BINARY_ELEMENTWISE and args:
            return self.binary_transfer([recv] + args[:1], node, fr, ctx)
        if name == "resplit_" or name == "resplit":
            axis_v = args[0] if args else kwargs.get("axis", Const(None))
            out = self.resplit_transfer(recv, axis_v, node, fr, inplace=name == "resplit_")
            if name == "resplit_" and isinstance(func.value, ast.Name):
                fr.env[func.value.id] = out
            return out
        if name == "astype":
            dtype = _dtype_from_node(node.args[0] if node.args else None)
            return recv.with_(dtype=dtype or recv.dtype, pending=True)
        if name == "reshape":
            shape = _const_shape(args[0]) if len(args) == 1 else _const_shape(
                VTuple(tuple(args))
            )
            return AbstractArray(
                rank=len(shape) if shape else None,
                split=TOP,
                shape=shape,
                dtype=recv.dtype,
            )
        if name == "transpose":
            return self._transpose(recv)
        if name in ("flatten", "ravel"):
            return AbstractArray(rank=1, split=TOP, dtype=recv.dtype)
        if name in ("balance_", "redistribute_"):
            return recv
        if name == "copy":
            return recv
        if name in ("get_halo",):
            fr.collective = True
            return Const(None)
        if name == "tolist":
            self._blocking(node, fr, ctx, "`.tolist()` host read")
            return UNKNOWN
        return UNKNOWN


# ----------------------------------------------------------------------
# entry points
# ----------------------------------------------------------------------
def _wanted_rules(rules) -> Optional[set]:
    if rules is None:
        return None
    wanted = (
        {r.strip().upper() for r in rules.split(",") if r.strip()}
        if isinstance(rules, str)
        else {r.strip().upper() for r in rules}
    )
    unknown = wanted - set(_RULE_BY_ID)
    if unknown:
        from .engine import LintError

        raise LintError(f"unknown rule id(s): {sorted(unknown)}")
    return wanted


def _finalize(an: Analyzer, graph: cg.CallGraph, rules=None) -> List[Finding]:
    wanted = _wanted_rules(rules)
    findings = [
        f for f in an.findings.values() if wanted is None or f.rule in wanted
    ]
    by_path: Dict[str, List[Finding]] = {}
    for f in findings:
        by_path.setdefault(f.path, []).append(f)
    for path, fs in by_path.items():
        mod = graph.modules.get(path)
        if mod is None:
            continue
        sup = _suppressions(mod.lines)
        if sup:
            for f in fs:
                f.suppressed = _is_suppressed(f, sup, mod.lines)
    findings.sort(key=lambda f: (f.path, f.line, f.col, f.rule))
    return findings


def verify_paths(
    paths,
    mesh_size: int = DEFAULT_MESH_SIZE,
    rules=None,
    budgets: Optional[Dict[str, int]] = None,
) -> Tuple[List[Finding], dict]:
    """Run the distribution-flow verifier over every ``.py`` file under
    ``paths``. Returns ``(findings, stats)``: engine-compatible
    :class:`Finding` objects (suppressions resolved, S1xx namespace) and a
    stats dict with per-region static cost bounds. ``budgets`` maps region
    globs to byte ceilings (S105). Pure standard library — never initializes
    a backend, never forces a chain."""
    graph = cg.build(paths)
    return _verify_graph(graph, mesh_size=mesh_size, rules=rules, budgets=budgets)


def verify_source(
    src: str,
    path: str = "<string>",
    mesh_size: int = DEFAULT_MESH_SIZE,
    rules=None,
    budgets: Optional[Dict[str, int]] = None,
    extra_sources: Optional[Dict[str, str]] = None,
) -> Tuple[List[Finding], dict]:
    """Verify one in-memory source (tests, drift workloads)."""
    sources = {path: src}
    if extra_sources:
        sources.update(extra_sources)
    graph = cg.build_from_sources(sources)
    return _verify_graph(graph, mesh_size=mesh_size, rules=rules, budgets=budgets)


def _verify_graph(graph, mesh_size, rules=None, budgets=None):
    _wanted_rules(rules)  # validate before paying for the analysis
    an = Analyzer(graph, mesh_size=mesh_size)
    for mod in graph.modules.values():
        an.analyze_module(mod)
    # default-context pass over every function, callees before callers so
    # context-capped summaries are already warm
    for scc in graph.sccs():
        for fn in scc:
            an.analyze_function(fn)
    findings = _finalize(an, graph, rules=rules)
    if budgets:
        findings.extend(_budget_findings(an, graph, budgets, rules))
        findings.sort(key=lambda f: (f.path, f.line, f.col, f.rule))
    regions = {
        name: rec for name, rec in sorted(an.regions.items()) if rec["bytes"] > 0
    }
    stats = {
        "mesh_size": an.p,
        "modules": len(graph.modules),
        "functions": len(graph.all_functions()),
        "contexts": len(an.summaries),
        "regions": regions,
        # region bounds OVERLAP by construction (a caller's bound merges its
        # callees'), so the total sums only the module-level regions — each
        # module's top-to-bottom execution, callees included exactly once
        "total_bytes": sum(
            rec["bytes"] for name, rec in regions.items() if name.endswith("::<module>")
        ),
    }
    return findings, stats


def _budget_findings(an: Analyzer, graph, budgets: Dict[str, int], rules=None) -> List[Finding]:
    wanted = _wanted_rules(rules)
    if wanted is not None and "S105" not in wanted:
        return []
    out: List[Finding] = []
    for pattern, ceiling in budgets.items():
        for region, rec in sorted(an.regions.items()):
            if not (
                fnmatch.fnmatch(region, pattern)
                or fnmatch.fnmatch(region.split("::")[-1], pattern)
            ):
                continue
            if rec["bytes"] <= ceiling:
                continue
            mod = graph.modules.get(rec["path"])
            lines = mod.lines if mod is not None else []
            line = rec["line"]
            f = Finding(
                rule="S105",
                path=rec["path"],
                line=line,
                col=0,
                severity="error",
                message=(
                    f"region `{region}` has a static bytes-on-wire lower "
                    f"bound of {rec['bytes']} B ({_fmt_cost(rec['cost'])}), "
                    f"over the {int(ceiling)} B budget for pattern "
                    f"{pattern!r}"
                ),
                hint=_RULE_BY_ID["S105"].hint,
                source=(lines[line - 1].strip() if 0 < line <= len(lines) else ""),
            )
            sup = _suppressions(lines) if lines else {}
            if sup:
                f.suppressed = _is_suppressed(f, sup, lines)
            out.append(f)
    return out


def _fmt_cost(cost: Dict[str, int]) -> str:
    return ", ".join(f"{op}: {b} B" for op, b in sorted(cost.items())) or "no collectives"


_BUDGET_SUFFIX = {"": 1, "B": 1, "KIB": 1 << 10, "MIB": 1 << 20, "GIB": 1 << 30,
                  "K": 1 << 10, "M": 1 << 20, "G": 1 << 30}


def parse_budget_arg(spec: str) -> Tuple[str, int]:
    """``GLOB=BYTES`` with optional KiB/MiB/GiB suffixes ->
    ``(glob, bytes)``."""
    if "=" not in spec:
        raise ValueError(f"budget {spec!r} is not GLOB=BYTES")
    glob, raw = spec.rsplit("=", 1)
    m = re.fullmatch(r"\s*([0-9.]+)\s*([A-Za-z]*)\s*", raw)
    if not m or m.group(2).upper() not in _BUDGET_SUFFIX:
        raise ValueError(f"budget bytes {raw!r} not understood (use e.g. 4096, 2MiB)")
    return glob.strip(), int(float(m.group(1)) * _BUDGET_SUFFIX[m.group(2).upper()])


# ----------------------------------------------------------------------
# the drift check: static estimates vs telemetry-observed bytes
# ----------------------------------------------------------------------
#: drift workloads: real collective-bearing computations whose observed
#: bytes telemetry records (the declared linalg schedules), written as
#: analyzable source so the SAME text feeds the abstract interpreter and a
#: live run. Shapes are baked per mesh size by :func:`workload_source`.
DRIFT_WORKLOADS: Dict[str, str] = {
    # CholeskyQR2's two Gram psums: allreduce 2 * n^2 * 4 bytes
    "qr_cholqr2": """
import heat_tpu as ht
ht.random.seed(7)
a = ht.random.randn({m}, {n}, split=0)
q, r = ht.linalg.qr(a, method="cholqr2")
""",
    # TSQR's R-factor gather: allgather p * min(m/p, n) * n * 4 bytes
    "qr_tsqr": """
import heat_tpu as ht
ht.random.seed(8)
a = ht.random.randn({m}, {n2}, split=0)
q, r = ht.linalg.qr(a, method="tsqr")
""",
    # blocked substitution: one (rows_loc, 1) psum per stage
    "solve_triangular": """
import heat_tpu as ht
A = ht.eye({ns}, split=0)
b = ht.ones(({ns},), split=0)
x = ht.linalg.solve_triangular(A, b, lower=True)
""",
}


def _workload_params(p: int) -> Dict[str, int]:
    return {"m": 64 * p, "n": 16, "n2": 12, "ns": 40 * p}


def workload_source(name: str, mesh_size: int) -> str:
    """The drift workload's source with shapes baked for ``mesh_size``."""
    return DRIFT_WORKLOADS[name].format(**_workload_params(max(1, mesh_size)))


def static_workload_bytes(name: str, mesh_size: int) -> Dict[str, int]:
    """The cost model's per-collective-type byte estimate for one drift
    workload — pure static analysis of the workload source."""
    src = workload_source(name, mesh_size)
    graph = cg.build_from_sources({f"<workload:{name}>": src})
    an = Analyzer(graph, mesh_size=mesh_size)
    for mod in graph.modules.values():
        an.analyze_module(mod)
    cost: Dict[str, int] = {}
    # module-level regions only: a caller's bound already merges its
    # callees', so summing function regions too would double-count any
    # workload that grows a helper
    for region, rec in an.regions.items():
        if not region.endswith("::<module>"):
            continue
        for op, b in rec["cost"].items():
            if op in OBSERVED_OPS:
                cost[op] = cost.get(op, 0) + b
    return cost


def observed_workload_bytes(name: str) -> Dict[str, int]:
    """Run one drift workload live under telemetry and return the observed
    per-collective-type bytes. The only function here that touches jax."""
    from heat_tpu.core import telemetry

    src = workload_source(name, _current_mesh_size())
    with telemetry.enabled():
        before = {
            op: rec.get("bytes", 0) for op, rec in telemetry.collectives().items()
        }
        exec(compile(src, f"<workload:{name}>", "exec"), {"__name__": "__drift__"})
        after = telemetry.collectives()
    out: Dict[str, int] = {}
    for op, rec in after.items():
        if op not in OBSERVED_OPS:
            continue
        delta = rec.get("bytes", 0) - before.get(op, 0)
        if delta > 0:
            out[op] = delta
    return out


def _current_mesh_size() -> int:
    import heat_tpu as ht

    return int(ht.get_comm().size)


def drift_report(workloads=None) -> dict:
    """Static-vs-observed byte drift over the drift workloads at the CURRENT
    mesh (initializes the backend). ``ratio`` is max(static, observed) /
    min(...); the acceptance bound is :data:`DRIFT_FACTOR`."""
    p = _current_mesh_size()
    doc = {"mesh_size": p, "workloads": {}}
    for name in workloads or DRIFT_WORKLOADS:
        static = static_workload_bytes(name, p)
        observed = observed_workload_bytes(name)
        doc["workloads"][name] = _drift_entry(static, observed)
    return doc


def _drift_entry(static: Dict[str, int], observed: Dict[str, int]) -> dict:
    s_total = sum(static.values())
    o_total = sum(observed.values())
    entry = {
        "static": static,
        "observed": observed,
        "static_total": s_total,
        "observed_total": o_total,
    }
    if s_total and o_total:
        entry["ratio"] = round(max(s_total, o_total) / min(s_total, o_total), 3)
        entry["drift_pct"] = round(100.0 * abs(s_total - o_total) / o_total, 1)
        entry["within_bound"] = entry["ratio"] <= DRIFT_FACTOR
    elif s_total == o_total:  # both zero (single-device mesh): no drift
        entry["ratio"] = 1.0
        entry["drift_pct"] = 0.0
        entry["within_bound"] = True
    else:
        # one side zero: incomparable — None (not float inf, which would
        # serialize as non-standard JSON `Infinity` in the saved artifact)
        entry["ratio"] = None
        entry["drift_pct"] = None
        entry["within_bound"] = False
    return entry


def compare_observed(report: dict) -> dict:
    """Diff static estimates against a SAVED observed report (the
    ``verify --observed`` path — fully static, no jax). The report is the
    :func:`drift_report`/``--save-observed`` JSON shape: its recorded
    mesh_size drives the static formulas."""
    p = int(report.get("mesh_size", DEFAULT_MESH_SIZE))
    doc = {"mesh_size": p, "workloads": {}}
    for name, rec in report.get("workloads", {}).items():
        if name not in DRIFT_WORKLOADS:
            continue
        observed = {
            op: int(b) for op, b in (rec.get("observed") or rec.get("collectives") or {}).items()
        }
        static = static_workload_bytes(name, p)
        doc["workloads"][name] = _drift_entry(static, observed)
    return doc

"""Pass 2 — the AOT sharded-program auditor.

The AST lint (:mod:`heat_tpu.analysis.rules`) catches hazards visible in the
*source*; this pass audits the *compiled artifacts*: every program in
fusion's sharded-program cache is AOT-lowered from its recorded abstract
signature (the memoized ``program_costs()`` machinery PR 6 built — no live
operands, nothing forced, nothing executed) and checked for the hazards only
the partitioned HLO can show:

* **Replication blowups** — a program with a split input whose per-host
  bytes-accessed is ≥ k× the sharded lower bound. The lower bound is
  measured, not guessed: the SAME signature is lowered a second time with
  every leaf fully replicated over its mesh, and that cost divided by the
  mesh size is what perfect sharding would pay per host — so chain depth
  (intermediate reads/writes inflate both lowerings equally) cancels out.
  A dropped ``with_sharding_constraint`` that replicates O(n) onto every
  host shows up as a ratio ≈ p; a healthy sharded chain sits at ≈ 1.
* **Collective parity across variants** — program variants of one op family
  with the same leaf-layout pattern and mesh must compile to the same
  per-type collective counts; a variant that grew or lost a collective is
  the compiled-side signature of host divergence (the same hazard H001
  flags in source, visible here even when the divergent branch lives in
  code the lint cannot see).
* **Bytes-on-wire budgets** — declared per-family budgets (collective
  counts and/or total on-wire bytes estimated from the collective
  instructions' result shapes in the optimized HLO) are diffed via
  ``telemetry.collective_budget_excess``.
* **Static memory peaks** — each program's XLA ``memory_analysis`` peak
  (arguments + outputs + temps per host, banked by ``fusion._estimate_cost``
  into ``cost["memory"]``) checked against a global ``--peak-budget``
  ceiling and/or per-family ``"peak_bytes"`` budget entries: the AOT form
  of the runtime ``HEAT_TPU_MEMORY_BUDGET`` admission gate
  (``core/memledger.py``), catching the program that would be refused at
  dispatch before anything runs it.

Everything here imports jax lazily — ``heat_tpu.analysis`` stays importable
(and the lint usable) on machines with no accelerator stack at all.
"""

from __future__ import annotations

import fnmatch
import re
from dataclasses import dataclass
from typing import Dict, List, Optional

__all__ = [
    "AuditFinding",
    "audit_programs",
    "render_audit",
    "warm_bench_cache",
]

#: flag when per-host bytes-accessed is at least this multiple of the
#: sharded lower bound (replicated-cost / mesh size). A healthy sharded
#: chain sits near 1.0; full replication sits near the mesh size.
DEFAULT_FACTOR = 2.0
#: ignore programs below this replicated-cost size: tiny programs are
#: constant-dominated and their ratios are noise, not layout decisions.
#: 256 KiB sits above scalar/constant noise while keeping the bench-warmed
#: programs (≈0.3–1 MiB replicated bytes-accessed at mesh 8) INSIDE the
#: audit — a floor above them would make the CI replication check vacuous
DEFAULT_MIN_BYTES = 1 << 18


@dataclass
class AuditFinding:
    """One program-level diagnostic, ``Finding``-shaped for the CLI."""

    kind: str  # "replication" | "collective_parity" | "budget"
    severity: str
    program: str  # the program key (fusion.cache_stats()["program_keys"])
    family: str
    message: str
    detail: dict

    @property
    def location(self) -> str:
        return f"<program:{self.program}>"

    def as_dict(self) -> dict:
        return {
            "kind": self.kind,
            "severity": self.severity,
            "program": self.program,
            "family": self.family,
            "message": self.message,
            "detail": self.detail,
        }


# ----------------------------------------------------------------------
# on-wire byte estimates from HLO collective instruction lines
# ----------------------------------------------------------------------
_HLO_SHAPE_RE = re.compile(r"\b([a-z]+\d*)\[([0-9,]*)\]")
_HLO_ITEMSIZE = {
    "pred": 1,
    "s8": 1, "u8": 1, "f8e4m3fn": 1, "f8e5m2": 1,
    "s16": 2, "u16": 2, "f16": 2, "bf16": 2,
    "s32": 4, "u32": 4, "f32": 4,
    "s64": 8, "u64": 8, "f64": 8, "c64": 8,
    "c128": 16,
}


def _hlo_line_bytes(line: str) -> int:
    """Bytes of the FIRST shaped value on an HLO instruction line — for a
    collective that is its result shape, the payload each participant puts
    on the wire (tuple-shaped results sum every element)."""
    total = 0
    # `name = (f32[8,4], f32[8]) all-reduce(...)` — consume shapes up to the
    # opcode; the first shape group before any '(' of the op call suffices
    head = line.split("=", 1)[-1]
    opcode_at = head.find("all-")
    for other in ("reduce-scatter", "collective-"):
        at = head.find(other)
        if at != -1 and (opcode_at == -1 or at < opcode_at):
            opcode_at = at
    if opcode_at > 0:
        head = head[:opcode_at]
    for m in _HLO_SHAPE_RE.finditer(head):
        dtype, dims = m.group(1), m.group(2)
        itemsize = _HLO_ITEMSIZE.get(dtype)
        if itemsize is None:
            continue
        size = 1
        for d in dims.split(","):
            if d:
                size *= int(d)
        total += size * itemsize
    return total


def _program_wire_bytes(cost: dict) -> Optional[int]:
    lines = cost.get("collective_lines")
    if lines is None:
        return None
    return sum(_hlo_line_bytes(line) for line in lines)


# ----------------------------------------------------------------------
# the audit
# ----------------------------------------------------------------------
def _layout_key(rec: dict) -> tuple:
    """The leaf-layout pattern of one program: per-leaf (ndim, replicated)
    plus the mesh size — shapes deliberately excluded, so size-variants of
    one family land in the same parity group."""
    return (
        rec["mesh_size"],
        tuple((len(leaf["shape"]), leaf["replicated"]) for leaf in rec["leaves"]),
    )


def audit_programs(
    factor: float = DEFAULT_FACTOR,
    min_bytes: int = DEFAULT_MIN_BYTES,
    budgets: Optional[Dict[str, dict]] = None,
    top: Optional[int] = None,
    peak_budget: Optional[int] = None,
) -> List[AuditFinding]:
    """Audit every cached sharded program (see the module docstring for the
    three checks). ``budgets`` maps an op-family glob to
    ``{"collectives": {type: max_count}, "wire_bytes": max_total,
    "peak_bytes": max_static_peak}`` (every key optional); ``peak_budget``
    applies one static-memory-peak ceiling (XLA ``memory_analysis``, per
    host) to EVERY program — the AOT form of the runtime admission gate
    (``HEAT_TPU_MEMORY_BUDGET``), catching a program that would blow the
    budget before anything dispatches it. Returns findings ranked
    errors-first. AOT only: nothing is executed, no live array is touched."""
    from heat_tpu.core import fusion, telemetry

    info = fusion.program_audit_info(top=top)
    findings: List[AuditFinding] = []

    # static memory peaks vs the global ceiling
    if peak_budget is not None:
        for key, rec in info.items():
            mem = rec["cost"].get("memory") or {}
            peak = mem.get("peak_bytes")
            if peak is None or peak <= peak_budget:
                continue
            findings.append(
                AuditFinding(
                    kind="memory",
                    severity="error",
                    program=key,
                    family=rec["family"],
                    message=(
                        f"static memory peak {int(peak)} B exceeds the "
                        f"{int(peak_budget)} B budget (arguments "
                        f"{mem.get('argument_bytes')} + outputs "
                        f"{mem.get('output_bytes')} + temps "
                        f"{mem.get('temp_bytes')} per host) — this program "
                        "would be refused (or OOM) at dispatch under "
                        "HEAT_TPU_MEMORY_BUDGET of the same size"
                    ),
                    detail={
                        "peak_bytes": int(peak),
                        "budget": int(peak_budget),
                        "memory": dict(mem),
                        "dispatches": rec["dispatches"],
                    },
                )
            )

    # replication blowups
    for key, rec in info.items():
        if not rec["split_leaves"] or rec["mesh_size"] <= 1:
            continue  # nothing is split: there is no sharding to drop
        cost, rcost = rec["cost"], rec["replicated_cost"]
        accessed = cost.get("bytes_accessed")
        repl_accessed = rcost.get("bytes_accessed")
        if accessed is None or not repl_accessed or repl_accessed < min_bytes:
            continue
        p = rec["mesh_size"]
        bound = repl_accessed / p
        ratio = accessed / bound if bound else 0.0
        if ratio >= factor:
            findings.append(
                AuditFinding(
                    kind="replication",
                    severity="error",
                    program=key,
                    family=rec["family"],
                    message=(
                        f"replication blowup: per-host bytes-accessed "
                        f"{int(accessed)} is {ratio:.1f}x the sharded lower bound "
                        f"{int(bound)} (mesh {p}) — a split input is being "
                        "materialized on every host; a sharding constraint was "
                        "dropped or a reshard-to-replicated snuck into the chain"
                    ),
                    detail={
                        "bytes_accessed": accessed,
                        "sharded_lower_bound": bound,
                        "ratio": round(ratio, 2),
                        "mesh_size": p,
                        "dispatches": rec["dispatches"],
                    },
                )
            )

    # collective parity across variants of one family
    groups: Dict[tuple, list] = {}
    for key, rec in info.items():
        if "error" in rec["cost"]:
            continue  # no compiled artifact to compare
        groups.setdefault((rec["family"],) + _layout_key(rec), []).append((key, rec))
    for (family, mesh_size, _layout), members in groups.items():
        if len(members) < 2:
            continue
        by_counts: Dict[tuple, list] = {}
        for key, rec in members:
            counts = tuple(sorted(rec["cost"].get("collectives", {}).items()))
            by_counts.setdefault(counts, []).append(key)
        if len(by_counts) > 1:
            variants = {
                ",".join(keys): dict(counts) for counts, keys in by_counts.items()
            }
            findings.append(
                AuditFinding(
                    kind="collective_parity",
                    severity="error",
                    program=next(iter(by_counts.values()))[0],
                    family=family,
                    message=(
                        f"collective-count mismatch across {len(members)} variants of "
                        f"one program family at mesh {mesh_size}: {variants} — the "
                        "compiled-side signature of host divergence (one variant "
                        "schedules collectives its siblings never join)"
                    ),
                    detail={"mesh_size": mesh_size, "variants": variants},
                )
            )

    # declared budgets
    for pattern, budget in (budgets or {}).items():
        for key, rec in info.items():
            if not fnmatch.fnmatch(rec["family"], pattern):
                continue
            counts = rec["cost"].get("collectives", {})
            allowed = budget.get("collectives")
            if allowed is not None:
                excess = telemetry.collective_budget_excess(counts, allowed)
                if excess:
                    findings.append(
                        AuditFinding(
                            kind="budget",
                            severity="error",
                            program=key,
                            family=rec["family"],
                            message=(
                                f"collective budget exceeded for family pattern "
                                f"{pattern!r}: {excess}"
                            ),
                            detail={"counts": counts, "budget": allowed, "excess": excess},
                        )
                    )
            max_peak = budget.get("peak_bytes")
            if max_peak is not None:
                peak = (rec["cost"].get("memory") or {}).get("peak_bytes")
                if peak is not None and peak > max_peak:
                    findings.append(
                        AuditFinding(
                            kind="budget",
                            severity="error",
                            program=key,
                            family=rec["family"],
                            message=(
                                f"static memory peak budget exceeded for family "
                                f"pattern {pattern!r}: {int(peak)} > {int(max_peak)} "
                                "bytes per host (XLA memory_analysis)"
                            ),
                            detail={"peak_bytes": int(peak), "budget": int(max_peak)},
                        )
                    )
            max_wire = budget.get("wire_bytes")
            if max_wire is not None:
                wire = _program_wire_bytes(rec["cost"])
                if wire is not None and wire > max_wire:
                    findings.append(
                        AuditFinding(
                            kind="budget",
                            severity="error",
                            program=key,
                            family=rec["family"],
                            message=(
                                f"bytes-on-wire budget exceeded for family pattern "
                                f"{pattern!r}: {wire} > {int(max_wire)} estimated from "
                                "the program's collective instruction shapes"
                            ),
                            detail={"wire_bytes": wire, "budget": max_wire},
                        )
                    )

    findings.sort(key=lambda f: (f.severity != "error", f.kind, f.family))
    return findings


def render_audit(findings: List[AuditFinding], audited: int) -> str:
    out = []
    for f in findings:
        out.append(f"{f.location}: {f.kind} {f.severity}: [{f.family}] {f.message}")
    out.append(
        f"heat-audit: {len(findings)} finding(s) over {audited} cached program(s)"
    )
    return "\n".join(out)


# ----------------------------------------------------------------------
# cache warming: the bench-shaped workloads
# ----------------------------------------------------------------------
def warm_bench_cache(rounds: int = 2) -> int:
    """Populate the sharded-program cache with the bench workloads' program
    shapes (eager chain, moments, reduction chain — the same op families
    bench.py measures), so a standalone ``python -m heat_tpu.analysis audit
    --warm bench`` audits a representative cache. Returns the number of
    cached programs afterwards. Deterministic data; a handful of dispatches."""
    import numpy as np

    import heat_tpu as ht
    from heat_tpu.core import fusion

    p = ht.get_comm().size
    # sized so every warmed program's replicated bytes-accessed clears
    # DEFAULT_MIN_BYTES at any matrix mesh — the audit must actually look
    # at these programs, not skip them under the small-program floor
    rows = 192 * max(p, 4)
    base = (
        np.linspace(-2.0, 3.0, rows * 64, dtype=np.float32).reshape(rows, 64) + 0.25
    )
    a = ht.array(base, split=0)
    for _ in range(max(1, rounds)):
        # the eager-chain bench's elementwise body
        x = ht.sqrt(ht.abs(a * 1.5 + 2.0)) - 0.5
        # heat-lint: disable=H002 — warming MUST force each round (that is the point)
        float(x.sum())
        # the moments bench: two reductions recorded, one sync
        m = ht.mean(a)
        s = ht.std(a)
        # heat-lint: disable=H002 — warming MUST force each round (that is the point)
        float(m) + float(s)
        # the reduction-chain bench: reduce feeding an elementwise consumer
        y = (a - ht.mean(a)) / (ht.std(a) + 1e-6)
        # heat-lint: disable=H002 — warming MUST force each round (that is the point)
        float(y.max())
    return len(fusion.cache_stats()["program_keys"])

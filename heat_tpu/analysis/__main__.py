"""``python -m heat_tpu.analysis`` — the SPMD hazard analyzer CLI.

.. code-block:: console

    $ python -m heat_tpu.analysis lint heat_tpu examples
    $ python -m heat_tpu.analysis lint heat_tpu examples --baseline
    $ python -m heat_tpu.analysis lint --write-baseline heat-lint-baseline.json heat_tpu examples
    $ python -m heat_tpu.analysis audit --warm bench --devices 8
    $ python -m heat_tpu.analysis verify heat_tpu examples --baseline
    $ python -m heat_tpu.analysis verify --budget '*KMeans.fit=64MiB' --json
    $ python -m heat_tpu.analysis verify --save-observed obs.json --devices 8
    $ python -m heat_tpu.analysis verify --observed obs.json
    $ python -m heat_tpu.analysis rules

``lint`` and ``verify`` are pure static analysis (no jax import, run
anywhere — ``verify`` is the interprocedural split/sharding abstract
interpreter, rules S101-S105, with ``--budget GLOB=BYTES`` static cost
ceilings); ``audit`` AOT-lowers the cached sharded programs, so it brings
up the (CPU-forced, or real) mesh — ``--devices N`` forces an N-device
host-platform mesh exactly like the test matrix does. ``verify
--save-observed`` is the one verify mode that initializes a backend: it
runs the drift workloads live so a later fully-static ``--observed`` diff
can pin the cost model against telemetry's bytes.

Exit codes: 0 = clean (or only suppressed/baselined findings), 1 = active
findings, 2 = usage/environment error.
"""

from __future__ import annotations

import argparse
import json
import os
import re
import sys
import time
from typing import List, Optional

DEFAULT_BASELINE = "heat-lint-baseline.json"
DEFAULT_PATHS = ["heat_tpu", "examples"]


def _cmd_lint(args, out) -> int:
    from . import engine

    paths = args.paths or DEFAULT_PATHS
    try:
        findings = engine.lint_paths(paths, rules=args.rules)
    except engine.LintError as exc:
        print(f"heat-lint: {exc}", file=out)
        return 2
    if args.write_baseline is not None:
        path = args.write_baseline or DEFAULT_BASELINE
        # namespace-scoped: the lint owns H-rule entries; the dataflow
        # verifier's S-rule entries in the shared file survive untouched
        doc = engine.write_baseline(path, findings, namespaces=("H",))
        print(
            f"heat-lint: baseline with {len(doc['entries'])} finding(s) written to {path}",
            file=out,
        )
        return 0
    if args.baseline is not None:
        try:
            baseline = engine.load_baseline(args.baseline or DEFAULT_BASELINE)
        except engine.LintError as exc:
            print(f"heat-lint: {exc}", file=out)
            return 2
        engine.apply_baseline(findings, baseline)
    if args.format == "json":
        print(
            json.dumps(
                {
                    "findings": [f.as_dict() for f in findings],
                    "summary": engine.summarize(findings),
                },
                indent=1,
            ),
            file=out,
        )
    else:
        print(engine.render_findings(findings, show_suppressed=args.show_suppressed), file=out)
    return 1 if engine.summarize(findings)["active"] else 0


def _force_mesh(devices: int) -> None:
    """Pin an N-device forced-host CPU mesh BEFORE the backend initializes
    (the same knobs tests/conftest.py uses); a no-op if jax already started."""
    flags = os.environ.get("XLA_FLAGS", "")
    flags = re.sub(r"--xla_force_host_platform_device_count=\d+", "", flags).strip()
    os.environ["XLA_FLAGS"] = (
        f"{flags} --xla_force_host_platform_device_count={devices}".strip()
    )
    os.environ.setdefault("JAX_PLATFORMS", "cpu")


def _cmd_audit(args, out) -> int:
    if args.devices:
        _force_mesh(args.devices)
    from . import audit as audit_mod

    budgets = None
    if args.budget:
        try:
            with open(args.budget) as fh:
                budgets = json.load(fh)
        except (OSError, json.JSONDecodeError) as exc:
            print(f"heat-audit: cannot read budget file {args.budget!r}: {exc}", file=out)
            return 2
    if args.warm == "bench":
        t0 = time.perf_counter()
        cached = audit_mod.warm_bench_cache()
        print(
            f"heat-audit: warmed {cached} program(s) with the bench workloads "
            f"in {time.perf_counter() - t0:.1f}s",
            file=out,
        )
    from heat_tpu.core import fusion

    peak_budget = None
    if args.peak_budget is not None:
        from heat_tpu.core import memledger

        try:
            peak_budget = memledger.parse_budget(args.peak_budget)
        except ValueError as exc:
            print(f"heat-audit: bad --peak-budget {args.peak_budget!r}: {exc}", file=out)
            return 2
        if not isinstance(peak_budget, int):
            print(
                f"heat-audit: --peak-budget must be absolute bytes "
                f"(got {args.peak_budget!r})",
                file=out,
            )
            return 2
    audited = len(fusion.cache_stats()["program_keys"])
    findings = audit_mod.audit_programs(
        factor=args.factor,
        min_bytes=args.min_bytes,
        budgets=budgets,
        top=args.top,
        peak_budget=peak_budget,
    )
    if args.format == "json":
        print(
            json.dumps({"findings": [f.as_dict() for f in findings], "audited": audited}, indent=1),
            file=out,
        )
    else:
        print(audit_mod.render_audit(findings, audited), file=out)
    return 1 if findings else 0


def _cmd_rules(args, out) -> int:
    from . import dataflow
    from .rules import rule_table

    print("— pass 1: AST lint (`lint`) —", file=out)
    for rec in rule_table():
        print(f"{rec['id']}  [{rec['severity']:<7}] {rec['title']}", file=out)
        print(f"      why:  {rec['rationale']}", file=out)
        print(f"      fix:  {rec['hint']}", file=out)
    print("— pass 3: distribution-flow verifier (`verify`) —", file=out)
    for rec in dataflow.rule_table():
        print(f"{rec['id']}  [{rec['severity']:<7}] {rec['title']}", file=out)
        print(f"      why:  {rec['rationale']}", file=out)
        print(f"      fix:  {rec['hint']}", file=out)
    return 0


def _cmd_verify(args, out) -> int:
    from . import dataflow, engine

    budgets = {}
    for spec in args.budget or []:
        try:
            glob, ceiling = dataflow.parse_budget_arg(spec)
        except ValueError as exc:
            print(f"heat-verify: {exc}", file=out)
            return 2
        budgets[glob] = ceiling

    if args.save_observed:
        # live telemetry capture of the drift workloads (the only verify
        # path that initializes a backend) — the saved report later feeds
        # the fully-static `--observed` diff
        if args.devices:
            _force_mesh(args.devices)
        rep = dataflow.drift_report()
        with open(args.save_observed, "w") as fh:
            json.dump(rep, fh, indent=1, sort_keys=True)
            fh.write("\n")
        print(
            f"heat-verify: observed collective bytes for "
            f"{len(rep['workloads'])} workload(s) at mesh "
            f"{rep['mesh_size']} written to {args.save_observed}",
            file=out,
        )
        return 0

    paths = args.paths or DEFAULT_PATHS
    try:
        findings, stats = dataflow.verify_paths(
            paths,
            mesh_size=args.mesh_size,
            rules=args.rules,
            budgets=budgets or None,
        )
    except engine.LintError as exc:
        print(f"heat-verify: {exc}", file=out)
        return 2

    if args.write_baseline is not None:
        path = args.write_baseline or DEFAULT_BASELINE
        # namespace-scoped: verify owns S-rule entries; the lint's H-rule
        # entries in the shared file survive untouched
        doc = engine.write_baseline(path, findings, namespaces=("S",))
        n = sum(1 for e in doc["entries"] if str(e.get("rule", "")).startswith("S"))
        print(
            f"heat-verify: baseline with {n} S-rule finding(s) written to {path}",
            file=out,
        )
        return 0
    if args.baseline is not None:
        try:
            baseline = engine.load_baseline(args.baseline or DEFAULT_BASELINE)
        except engine.LintError as exc:
            print(f"heat-verify: {exc}", file=out)
            return 2
        engine.apply_baseline(findings, baseline)

    drift = None
    drift_ok = True
    if args.observed:
        try:
            with open(args.observed) as fh:
                report = json.load(fh)
        except (OSError, json.JSONDecodeError) as exc:
            print(f"heat-verify: cannot read observed report {args.observed!r}: {exc}", file=out)
            return 2
        drift = dataflow.compare_observed(report)
        drift_ok = all(
            rec.get("within_bound", False) for rec in drift["workloads"].values()
        ) and bool(drift["workloads"])

    if args.format == "json":
        print(
            json.dumps(
                {
                    "findings": [f.as_dict() for f in findings],
                    "summary": engine.summarize(findings),
                    "stats": stats,
                    "drift": drift,
                },
                indent=1,
            ),
            file=out,
        )
    else:
        print(
            engine.render_findings(
                findings, show_suppressed=args.show_suppressed, prog="heat-verify"
            ),
            file=out,
        )
        top = sorted(
            stats["regions"].items(), key=lambda kv: -kv[1]["bytes"]
        )[: args.top_regions]
        if top:
            print("costliest regions (static bytes-on-wire lower bound):", file=out)
            for name, rec in top:
                ops = ", ".join(
                    f"{op}={b}" for op, b in sorted(rec["cost"].items())
                )
                print(f"  {rec['bytes']:>12} B  {name}  ({ops})", file=out)
        if drift is not None:
            print(
                f"static-vs-observed drift at mesh {drift['mesh_size']} "
                f"(bound: {dataflow.DRIFT_FACTOR}x):",
                file=out,
            )
            for name, rec in sorted(drift["workloads"].items()):
                mark = "ok" if rec.get("within_bound") else "DRIFT"
                print(
                    f"  {name}: static {rec['static_total']} B vs observed "
                    f"{rec['observed_total']} B (ratio {rec.get('ratio')}, "
                    f"{rec.get('drift_pct')}%) {mark}",
                    file=out,
                )
    if not drift_ok:
        return 1
    return 1 if engine.summarize(findings)["active"] else 0


def main(argv: Optional[List[str]] = None, out=None) -> int:
    out = out if out is not None else sys.stdout
    parser = argparse.ArgumentParser(
        prog="python -m heat_tpu.analysis",
        description="SPMD hazard analyzer: AST lint (H001-H005) + AOT sharded-program audit.",
    )
    sub = parser.add_subparsers(dest="cmd", required=True)

    p_lint = sub.add_parser("lint", help="lint Python sources for SPMD hazards")
    p_lint.add_argument("paths", nargs="*", help=f"files/dirs (default: {' '.join(DEFAULT_PATHS)})")
    p_lint.add_argument(
        "--baseline",
        nargs="?",
        const=DEFAULT_BASELINE,
        default=None,
        metavar="FILE",
        help=f"fail only on findings NOT in this baseline (default file: {DEFAULT_BASELINE})",
    )
    p_lint.add_argument(
        "--write-baseline",
        nargs="?",
        const=DEFAULT_BASELINE,
        default=None,
        metavar="FILE",
        help="write the current findings as the new baseline and exit 0",
    )
    p_lint.add_argument("--rules", help="comma list of rule ids to run (default: all)")
    p_lint.add_argument("--format", choices=("text", "json"), default="text")
    p_lint.add_argument(
        "--show-suppressed", action="store_true", help="also print suppressed/baselined findings"
    )

    p_audit = sub.add_parser("audit", help="AOT-audit the cached sharded programs")
    p_audit.add_argument(
        "--devices", type=int, default=0, help="force an N-device host-platform CPU mesh"
    )
    p_audit.add_argument(
        "--warm",
        choices=("none", "bench"),
        default="none",
        help="'bench' warms the cache with the bench-shaped workloads first",
    )
    p_audit.add_argument(
        "--factor",
        type=float,
        default=None,
        help="replication-blowup threshold: per-host bytes-accessed >= FACTOR x sharded lower bound",
    )
    p_audit.add_argument(
        "--min-bytes", type=int, default=None, help="ignore programs smaller than this"
    )
    p_audit.add_argument("--budget", metavar="FILE", help="JSON family-glob -> collective/wire-bytes/peak-bytes budgets")
    p_audit.add_argument(
        "--peak-budget",
        metavar="BYTES",
        default=None,
        help="flag any program whose static memory peak (XLA memory_analysis, "
        "per host) exceeds this — accepts KiB/MiB/GiB suffixes, the AOT form "
        "of HEAT_TPU_MEMORY_BUDGET",
    )
    p_audit.add_argument("--top", type=int, default=None, help="audit only the top-N programs by dispatches")
    p_audit.add_argument("--format", choices=("text", "json"), default="text")

    p_verify = sub.add_parser(
        "verify",
        help="distribution-flow verifier: interprocedural split/sharding "
        "abstract interpretation (S101-S105) + static cost budgets",
    )
    p_verify.add_argument(
        "paths", nargs="*", help=f"files/dirs (default: {' '.join(DEFAULT_PATHS)})"
    )
    p_verify.add_argument(
        "--baseline",
        nargs="?",
        const=DEFAULT_BASELINE,
        default=None,
        metavar="FILE",
        help=f"fail only on findings NOT in this baseline (default file: {DEFAULT_BASELINE}; "
        "shared with the lint — namespaces are disjoint)",
    )
    p_verify.add_argument(
        "--write-baseline",
        nargs="?",
        const=DEFAULT_BASELINE,
        default=None,
        metavar="FILE",
        help="write the current S-rule findings into the baseline (H-rule entries preserved) and exit 0",
    )
    p_verify.add_argument(
        "--budget",
        action="append",
        metavar="GLOB=BYTES",
        help="static cost budget: fail when a region matching GLOB (function "
        "qualname, e.g. '*KMeans.fit') exceeds BYTES on the wire "
        "(KiB/MiB/GiB suffixes ok); repeatable",
    )
    p_verify.add_argument(
        "--mesh-size",
        type=int,
        default=None,
        help="mesh size the cost formulas assume (default: 8)",
    )
    p_verify.add_argument(
        "--observed",
        metavar="FILE",
        help="diff the static byte estimates against a saved telemetry report "
        "(produced by --save-observed); fails when any workload drifts past "
        "the 2x bound",
    )
    p_verify.add_argument(
        "--save-observed",
        metavar="FILE",
        help="run the drift workloads live under telemetry (initializes the "
        "backend!) and save the observed collective bytes, then exit",
    )
    p_verify.add_argument(
        "--devices", type=int, default=0, help="with --save-observed: force an N-device host-platform CPU mesh"
    )
    p_verify.add_argument("--rules", help="comma list of S-rule ids to run (default: all)")
    p_verify.add_argument(
        "--top-regions", type=int, default=5, help="text mode: show the N costliest regions"
    )
    p_verify.add_argument("--format", choices=("text", "json"), default="text")
    p_verify.add_argument(
        "--json", dest="format", action="store_const", const="json", help="alias for --format json"
    )
    p_verify.add_argument(
        "--show-suppressed", action="store_true", help="also print suppressed/baselined findings"
    )

    sub.add_parser("rules", help="print both passes' rule tables")

    args = parser.parse_args(argv)
    if args.cmd == "lint":
        return _cmd_lint(args, out)
    if args.cmd == "audit":
        from . import audit as audit_mod

        if args.factor is None:
            args.factor = audit_mod.DEFAULT_FACTOR
        if args.min_bytes is None:
            args.min_bytes = audit_mod.DEFAULT_MIN_BYTES
        return _cmd_audit(args, out)
    if args.cmd == "verify":
        if args.mesh_size is None:
            from .dataflow import DEFAULT_MESH_SIZE

            args.mesh_size = DEFAULT_MESH_SIZE
        return _cmd_verify(args, out)
    if args.cmd == "rules":
        return _cmd_rules(args, out)
    return 2  # pragma: no cover - argparse enforces the subcommands


if __name__ == "__main__":  # pragma: no cover - exercised via subprocess in CI
    sys.exit(main())

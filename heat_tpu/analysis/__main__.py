"""``python -m heat_tpu.analysis`` — the SPMD hazard analyzer CLI.

.. code-block:: console

    $ python -m heat_tpu.analysis lint heat_tpu examples
    $ python -m heat_tpu.analysis lint heat_tpu examples --baseline
    $ python -m heat_tpu.analysis lint --write-baseline heat-lint-baseline.json heat_tpu examples
    $ python -m heat_tpu.analysis audit --warm bench --devices 8
    $ python -m heat_tpu.analysis rules

``lint`` is pure AST analysis (no jax import, runs anywhere); ``audit``
AOT-lowers the cached sharded programs, so it brings up the (CPU-forced, or
real) mesh — ``--devices N`` forces an N-device host-platform mesh exactly
like the test matrix does.

Exit codes: 0 = clean (or only suppressed/baselined findings), 1 = active
findings, 2 = usage/environment error.
"""

from __future__ import annotations

import argparse
import json
import os
import re
import sys
import time
from typing import List, Optional

DEFAULT_BASELINE = "heat-lint-baseline.json"
DEFAULT_PATHS = ["heat_tpu", "examples"]


def _cmd_lint(args, out) -> int:
    from . import engine

    paths = args.paths or DEFAULT_PATHS
    try:
        findings = engine.lint_paths(paths, rules=args.rules)
    except engine.LintError as exc:
        print(f"heat-lint: {exc}", file=out)
        return 2
    if args.write_baseline is not None:
        path = args.write_baseline or DEFAULT_BASELINE
        doc = engine.write_baseline(path, findings)
        print(
            f"heat-lint: baseline with {len(doc['entries'])} finding(s) written to {path}",
            file=out,
        )
        return 0
    if args.baseline is not None:
        try:
            baseline = engine.load_baseline(args.baseline or DEFAULT_BASELINE)
        except engine.LintError as exc:
            print(f"heat-lint: {exc}", file=out)
            return 2
        engine.apply_baseline(findings, baseline)
    if args.format == "json":
        print(
            json.dumps(
                {
                    "findings": [f.as_dict() for f in findings],
                    "summary": engine.summarize(findings),
                },
                indent=1,
            ),
            file=out,
        )
    else:
        print(engine.render_findings(findings, show_suppressed=args.show_suppressed), file=out)
    return 1 if engine.summarize(findings)["active"] else 0


def _force_mesh(devices: int) -> None:
    """Pin an N-device forced-host CPU mesh BEFORE the backend initializes
    (the same knobs tests/conftest.py uses); a no-op if jax already started."""
    flags = os.environ.get("XLA_FLAGS", "")
    flags = re.sub(r"--xla_force_host_platform_device_count=\d+", "", flags).strip()
    os.environ["XLA_FLAGS"] = (
        f"{flags} --xla_force_host_platform_device_count={devices}".strip()
    )
    os.environ.setdefault("JAX_PLATFORMS", "cpu")


def _cmd_audit(args, out) -> int:
    if args.devices:
        _force_mesh(args.devices)
    from . import audit as audit_mod

    budgets = None
    if args.budget:
        try:
            with open(args.budget) as fh:
                budgets = json.load(fh)
        except (OSError, json.JSONDecodeError) as exc:
            print(f"heat-audit: cannot read budget file {args.budget!r}: {exc}", file=out)
            return 2
    if args.warm == "bench":
        t0 = time.perf_counter()
        cached = audit_mod.warm_bench_cache()
        print(
            f"heat-audit: warmed {cached} program(s) with the bench workloads "
            f"in {time.perf_counter() - t0:.1f}s",
            file=out,
        )
    from heat_tpu.core import fusion

    peak_budget = None
    if args.peak_budget is not None:
        from heat_tpu.core import memledger

        try:
            peak_budget = memledger.parse_budget(args.peak_budget)
        except ValueError as exc:
            print(f"heat-audit: bad --peak-budget {args.peak_budget!r}: {exc}", file=out)
            return 2
        if not isinstance(peak_budget, int):
            print(
                f"heat-audit: --peak-budget must be absolute bytes "
                f"(got {args.peak_budget!r})",
                file=out,
            )
            return 2
    audited = len(fusion.cache_stats()["program_keys"])
    findings = audit_mod.audit_programs(
        factor=args.factor,
        min_bytes=args.min_bytes,
        budgets=budgets,
        top=args.top,
        peak_budget=peak_budget,
    )
    if args.format == "json":
        print(
            json.dumps({"findings": [f.as_dict() for f in findings], "audited": audited}, indent=1),
            file=out,
        )
    else:
        print(audit_mod.render_audit(findings, audited), file=out)
    return 1 if findings else 0


def _cmd_rules(args, out) -> int:
    from .rules import rule_table

    for rec in rule_table():
        print(f"{rec['id']}  [{rec['severity']:<7}] {rec['title']}", file=out)
        print(f"      why:  {rec['rationale']}", file=out)
        print(f"      fix:  {rec['hint']}", file=out)
    return 0


def main(argv: Optional[List[str]] = None, out=None) -> int:
    out = out if out is not None else sys.stdout
    parser = argparse.ArgumentParser(
        prog="python -m heat_tpu.analysis",
        description="SPMD hazard analyzer: AST lint (H001-H005) + AOT sharded-program audit.",
    )
    sub = parser.add_subparsers(dest="cmd", required=True)

    p_lint = sub.add_parser("lint", help="lint Python sources for SPMD hazards")
    p_lint.add_argument("paths", nargs="*", help=f"files/dirs (default: {' '.join(DEFAULT_PATHS)})")
    p_lint.add_argument(
        "--baseline",
        nargs="?",
        const=DEFAULT_BASELINE,
        default=None,
        metavar="FILE",
        help=f"fail only on findings NOT in this baseline (default file: {DEFAULT_BASELINE})",
    )
    p_lint.add_argument(
        "--write-baseline",
        nargs="?",
        const=DEFAULT_BASELINE,
        default=None,
        metavar="FILE",
        help="write the current findings as the new baseline and exit 0",
    )
    p_lint.add_argument("--rules", help="comma list of rule ids to run (default: all)")
    p_lint.add_argument("--format", choices=("text", "json"), default="text")
    p_lint.add_argument(
        "--show-suppressed", action="store_true", help="also print suppressed/baselined findings"
    )

    p_audit = sub.add_parser("audit", help="AOT-audit the cached sharded programs")
    p_audit.add_argument(
        "--devices", type=int, default=0, help="force an N-device host-platform CPU mesh"
    )
    p_audit.add_argument(
        "--warm",
        choices=("none", "bench"),
        default="none",
        help="'bench' warms the cache with the bench-shaped workloads first",
    )
    p_audit.add_argument(
        "--factor",
        type=float,
        default=None,
        help="replication-blowup threshold: per-host bytes-accessed >= FACTOR x sharded lower bound",
    )
    p_audit.add_argument(
        "--min-bytes", type=int, default=None, help="ignore programs smaller than this"
    )
    p_audit.add_argument("--budget", metavar="FILE", help="JSON family-glob -> collective/wire-bytes/peak-bytes budgets")
    p_audit.add_argument(
        "--peak-budget",
        metavar="BYTES",
        default=None,
        help="flag any program whose static memory peak (XLA memory_analysis, "
        "per host) exceeds this — accepts KiB/MiB/GiB suffixes, the AOT form "
        "of HEAT_TPU_MEMORY_BUDGET",
    )
    p_audit.add_argument("--top", type=int, default=None, help="audit only the top-N programs by dispatches")
    p_audit.add_argument("--format", choices=("text", "json"), default="text")

    sub.add_parser("rules", help="print the rule table")

    args = parser.parse_args(argv)
    if args.cmd == "lint":
        return _cmd_lint(args, out)
    if args.cmd == "audit":
        from . import audit as audit_mod

        if args.factor is None:
            args.factor = audit_mod.DEFAULT_FACTOR
        if args.min_bytes is None:
            args.min_bytes = audit_mod.DEFAULT_MIN_BYTES
        return _cmd_audit(args, out)
    if args.cmd == "rules":
        return _cmd_rules(args, out)
    return 2  # pragma: no cover - argparse enforces the subcommands


if __name__ == "__main__":  # pragma: no cover - exercised via subprocess in CI
    sys.exit(main())

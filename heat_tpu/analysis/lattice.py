"""The distribution-flow value lattice.

The dataflow verifier (:mod:`heat_tpu.analysis.dataflow`) tracks every value
a program manipulates through a small abstract domain. For DNDarrays the
element is the tuple the ISSUE names::

    (rank, split ∈ {None, 0..k, ⊤}, device-set, pending|forced)

plus the statically-known parts of the shape and dtype, because the static
cost model prices collectives in bytes and bytes need dims × itemsize.
``split`` is the load-bearing coordinate: heat's single-integer split makes
distribution semantics statically decidable (HeAT, arxiv 2007.13552) — two
concrete-but-different splits meeting at a binary op IS the implicit-reshard
hazard (S101), a concrete split collapsing to ``None`` IS the downgrade
hazard (S103). ``⊤`` (:data:`TOP`) means "some split, statically unknown";
rules only fire on *concrete* disagreement, so ⊤ is how the interpreter
stays conservative instead of wrong.

Non-array values keep just enough structure for the rules: literal constants
(:class:`Const`) so shapes/splits/axes written in source propagate into the
cost model, scalars with a host-divergence taint (:class:`Scalar` — the S104
"two abstract hosts" bit, with provenance recording whether the divergence
crossed a function boundary), tuples (:class:`VTuple`) so ``q, r = qr(a)``
unpacks, class instances (:class:`Instance`) so estimator ``self`` state
flows through methods, and :data:`UNKNOWN` as the top of the whole domain.

Pure standard library; importing this module never touches jax.
"""

from __future__ import annotations

from dataclasses import dataclass, field, replace
from typing import Dict, Optional, Tuple, Union

__all__ = [
    "TOP",
    "UNKNOWN",
    "AbstractArray",
    "Const",
    "Instance",
    "Scalar",
    "VTuple",
    "as_array",
    "bcast_shape",
    "is_divergent",
    "itemsize",
    "join",
    "logical_bytes",
]


class _Top:
    """⊤ of the split sub-lattice: distributed along SOME axis, unknown
    which. Distinct from ``None`` (known replicated) and from an int (known
    axis). A singleton so identity checks work."""

    _instance = None

    def __new__(cls):
        if cls._instance is None:
            cls._instance = super().__new__(cls)
        return cls._instance

    def __repr__(self):
        return "⊤"

    def __reduce__(self):  # keep the singleton through copy/pickle
        return (_Top, ())


TOP = _Top()

#: split domain: None (replicated) | int (axis) | TOP (unknown)
Split = Union[None, int, _Top]


class _Unknown:
    """⊤ of the full value domain: could be anything, including a DNDarray
    of any layout. Rules never fire on it."""

    _instance = None

    def __new__(cls):
        if cls._instance is None:
            cls._instance = super().__new__(cls)
        return cls._instance

    def __repr__(self):
        return "?"

    def __reduce__(self):
        return (_Unknown, ())


UNKNOWN = _Unknown()


@dataclass(frozen=True)
class Const:
    """A statically-known python literal (int/float/str/bool/None/tuples of
    those). Shapes, split axes and method kwargs travel as Consts."""

    value: object

    def __repr__(self):
        return f"Const({self.value!r})"


@dataclass(frozen=True)
class Scalar:
    """A non-array runtime value the analysis does not model further, except
    for the host-divergence taint: ``divergent=True`` means the value differs
    across controller processes of one SPMD job (process identity, wall
    clock, unseeded RNG). ``via_call`` records whether that divergence came
    out of a *callee's return value* — the provenance bit S104 uses to report
    only hazards H001's intraprocedural view cannot see."""

    divergent: bool = False
    via_call: bool = False


@dataclass(frozen=True)
class AbstractArray:
    """One DNDarray as the verifier sees it.

    ``rank``/``shape`` are ``None`` when unknown; known shapes may carry
    ``None`` for individual unknown dims. ``split`` is the three-valued
    distribution coordinate. ``pending`` distinguishes a recorded-but-not-
    forced fusion chain from a materialized value (host reads of pending
    values are the blocking syncs S102 prices). ``device`` is the device-set
    tag: ``"mesh"`` for arrays living on the SPMD mesh, ``"host"`` for
    host-materialized copies, ``None`` when unknown."""

    rank: Optional[int] = None
    split: Split = TOP
    shape: Optional[Tuple[Optional[int], ...]] = None
    dtype: Optional[str] = None
    pending: bool = True
    device: Optional[str] = "mesh"

    def with_(self, **kw) -> "AbstractArray":
        return replace(self, **kw)


@dataclass
class Instance:
    """An object of an analyzed class: ``attrs`` is the flow-insensitive
    abstract heap for ``self.<name>`` (joined at every write, never killed),
    so estimator state like fitted centroids keeps its layout across
    methods. Deliberately mutable + compared by content."""

    cls: str
    attrs: Dict[str, object] = field(default_factory=dict)

    def __eq__(self, other):
        return (
            isinstance(other, Instance)
            and self.cls == other.cls
            and self.attrs == other.attrs
        )

    def __repr__(self):
        return f"Instance({self.cls}, {sorted(self.attrs)})"


@dataclass(frozen=True)
class VTuple:
    """A fixed-arity tuple of abstract values (function multi-returns,
    ``shape`` literals, unpacking targets)."""

    items: Tuple[object, ...]


# ----------------------------------------------------------------------
# byte helpers for the cost model
# ----------------------------------------------------------------------
_ITEMSIZE = {
    "bool": 1,
    "int8": 1, "uint8": 1,
    "int16": 2, "uint16": 2, "float16": 2, "bfloat16": 2,
    "int32": 4, "uint32": 4, "float32": 4,
    "int64": 8, "uint64": 8, "float64": 8, "complex64": 8,
    "complex128": 16,
}


def itemsize(dtype: Optional[str], default: int = 4) -> int:
    """Bytes per element; unknown dtypes price at the f32 default — the cost
    model is a lower bound, not an oracle."""
    if dtype is None:
        return default
    return _ITEMSIZE.get(dtype, default)


def logical_bytes(arr: "AbstractArray") -> Optional[int]:
    """Global logical payload bytes (the convention telemetry's collective
    accounting uses), or None when any dim is unknown."""
    if not isinstance(arr, AbstractArray) or arr.shape is None:
        return None
    total = 1
    for d in arr.shape:
        if d is None:
            return None
        total *= int(d)
    return total * itemsize(arr.dtype)


def as_array(v) -> Optional[AbstractArray]:
    return v if isinstance(v, AbstractArray) else None


def is_divergent(v) -> bool:
    if isinstance(v, Scalar):
        return v.divergent
    if isinstance(v, VTuple):
        return any(is_divergent(i) for i in v.items)
    return False


def bcast_shape(
    a: Optional[Tuple[Optional[int], ...]], b: Optional[Tuple[Optional[int], ...]]
) -> Optional[Tuple[Optional[int], ...]]:
    """Numpy broadcast of two partially-known shapes; None when either side
    is wholly unknown, per-dim None where the dims are."""
    if a is None or b is None:
        return None
    if len(a) < len(b):
        a = (1,) * (len(b) - len(a)) + tuple(a)
    elif len(b) < len(a):
        b = (1,) * (len(a) - len(b)) + tuple(b)
    out = []
    for x, y in zip(a, b):
        if x is None or y is None:
            out.append(None)
        elif x == 1:
            out.append(y)
        elif y == 1 or x == y:
            out.append(x)
        else:
            return None  # statically incompatible: let the runtime error
    return tuple(out)


# ----------------------------------------------------------------------
# join / widen
# ----------------------------------------------------------------------
def _join_split(a: Split, b: Split) -> Split:
    return a if a == b else TOP


def _join_opt(a, b):
    return a if a == b else None


def _join_shape(a, b):
    if a is None or b is None or len(a) != len(b):
        return None
    return tuple(x if x == y else None for x, y in zip(a, b))


def join(a, b):
    """Least upper bound of two abstract values: control-flow merge. Equal
    values join to themselves; structurally-compatible arrays merge
    coordinate-wise (split disagreement → ⊤); everything else tops out at
    :data:`UNKNOWN`. Every sub-lattice here is FLAT (a value, or its top),
    so join doubles as the loop-widening operator: a coordinate that
    changes across iterations reaches its top after one join, which is
    what bounds the interpreter's fixpoint."""
    if a is b or a == b:
        return a
    if isinstance(a, AbstractArray) and isinstance(b, AbstractArray):
        return AbstractArray(
            rank=_join_opt(a.rank, b.rank),
            split=_join_split(a.split, b.split),
            shape=_join_shape(a.shape, b.shape),
            dtype=_join_opt(a.dtype, b.dtype),
            pending=a.pending or b.pending,
            device=_join_opt(a.device, b.device),
        )
    if isinstance(a, Scalar) and isinstance(b, Scalar):
        return Scalar(
            divergent=a.divergent or b.divergent,
            via_call=a.via_call or b.via_call,
        )
    if isinstance(a, Const) and isinstance(b, Scalar):
        return b
    if isinstance(a, Scalar) and isinstance(b, Const):
        return a
    if isinstance(a, VTuple) and isinstance(b, VTuple) and len(a.items) == len(b.items):
        return VTuple(tuple(join(x, y) for x, y in zip(a.items, b.items)))
    if isinstance(a, Instance) and isinstance(b, Instance) and a.cls == b.cls:
        attrs = dict(a.attrs)
        for k, v in b.attrs.items():
            attrs[k] = join(attrs[k], v) if k in attrs else v
        return Instance(a.cls, attrs)
    return UNKNOWN


def join_env(a: Dict[str, object], b: Dict[str, object]) -> Dict[str, object]:
    """Pointwise join of two environments; names bound on only one path
    join with "unbound" and become UNKNOWN (they may not exist at runtime)."""
    out: Dict[str, object] = {}
    for name in set(a) | set(b):
        if name in a and name in b:
            out[name] = join(a[name], b[name])
        else:
            out[name] = UNKNOWN
    return out

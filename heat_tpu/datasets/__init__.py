"""Bundled datasets (reference: heat/datasets/ ships iris.csv/h5/nc and
diabetes.h5 as static files for tests and examples).

Two tiers:

* **Real bundled files** under ``datasets/data/`` — the canonical
  public-domain Fisher iris measurements (CSV semicolon layout, HDF5, and
  classic-NETCDF3 ``iris.nc``) and the standardized diabetes regression data,
  the same datasets the reference ships. Load via :func:`load_iris` /
  :func:`load_diabetes`, or point ``ht.load`` at :func:`path` directly.
* **Deterministic synthetic analogs** (:func:`iris_like` /
  :func:`diabetes_like`) for tests that want a seeded generator instead of
  fixed data, plus :func:`materialize` to write them out for I/O exercises.
"""

from __future__ import annotations

import os
from typing import Optional, Tuple

import numpy as np

from ..core import factories
from ..core.dndarray import DNDarray

__all__ = [
    "iris_like",
    "diabetes_like",
    "materialize",
    "load_iris",
    "load_diabetes",
    "path",
]

_DATA_DIR = os.path.join(os.path.dirname(os.path.abspath(__file__)), "data")


def path(name: str) -> str:
    """Absolute path of a bundled dataset file (``iris.csv``, ``iris.h5``,
    ``iris.nc``, ``iris_labels.csv``, ``diabetes.h5``) — the analog of the
    reference's ``heat/datasets/<file>`` relative paths."""
    p = os.path.join(_DATA_DIR, name)
    if not os.path.exists(p):
        raise FileNotFoundError(
            f"no bundled dataset {name!r}; available: {sorted(os.listdir(_DATA_DIR))}"
        )
    return p


def load_iris(split: Optional[int] = None, return_labels: bool = False):
    """The real Fisher iris dataset (150, 4) from the bundled files —
    the dataset the reference's estimator tests run on (reference
    cluster/tests/test_kmeans.py:80 loads heat/datasets/iris.csv)."""
    from ..core import io

    data = io.load_csv(path("iris.csv"), sep=";", split=split)
    if not return_labels:
        return data
    y = np.loadtxt(path("iris_labels.csv"), dtype=np.int64)
    # the 1-D labels share the sample axis only: split=0 follows, split=1
    # (a feature split of the 2-D data) leaves them replicated
    return data, factories.array(y.astype(np.int32), split=0 if split == 0 else None)


def load_diabetes(split: Optional[int] = None, return_y: bool = False):
    """The real diabetes regression dataset (442, 11 incl. intercept column)
    from the bundled HDF5 (reference heat/datasets/diabetes.h5)."""
    from ..core import io

    x = io.load_hdf5(path("diabetes.h5"), "x", split=split)
    if not return_y:
        return x
    return x, io.load_hdf5(
        path("diabetes.h5"), "y", split=0 if split == 0 else None
    )

_IRIS_CENTERS = np.array(
    [
        [5.0, 3.4, 1.5, 0.25],
        [5.9, 2.8, 4.3, 1.3],
        [6.6, 3.0, 5.6, 2.0],
    ],
    dtype=np.float32,
)
_IRIS_STD = np.array([0.35, 0.35, 0.3, 0.2], dtype=np.float32)


def iris_like(split: Optional[int] = None, return_labels: bool = False):
    """A deterministic (150, 4) three-class dataset with iris-like cluster
    geometry, for estimator convergence tests (stand-in for the reference's
    heat/datasets/iris.h5)."""
    rng = np.random.default_rng(1234)
    xs, ys = [], []
    for i, c in enumerate(_IRIS_CENTERS):
        xs.append(rng.normal(c, _IRIS_STD, size=(50, 4)).astype(np.float32))
        ys.append(np.full(50, i, dtype=np.int32))
    x = np.concatenate(xs)
    y = np.concatenate(ys)
    data = factories.array(x, split=split)
    if return_labels:
        return data, factories.array(y, split=split)
    return data


def diabetes_like(split: Optional[int] = None):
    """A deterministic (442, 10) standardized regression dataset (stand-in for
    the reference's heat/datasets/diabetes.h5)."""
    rng = np.random.default_rng(5678)
    x = rng.standard_normal((442, 10)).astype(np.float32)
    x = (x - x.mean(0)) / x.std(0)
    return factories.array(x, split=split)


def materialize(directory: str) -> dict:
    """Write the generated datasets as iris.csv/iris.h5/diabetes.h5 under
    ``directory`` and return the paths — mirrors the reference's on-disk
    layout for I/O tests and examples."""
    from ..core import io

    os.makedirs(directory, exist_ok=True)
    paths = {}
    iris = iris_like()
    iris_csv = os.path.join(directory, "iris.csv")
    io.save_csv(iris, iris_csv)
    paths["iris.csv"] = iris_csv
    if io.supports_hdf5():
        iris_h5 = os.path.join(directory, "iris.h5")
        io.save_hdf5(iris, iris_h5, "data")
        paths["iris.h5"] = iris_h5
        diabetes_h5 = os.path.join(directory, "diabetes.h5")
        io.save_hdf5(diabetes_like(), diabetes_h5, "x")
        paths["diabetes.h5"] = diabetes_h5
    return paths

"""Bundled datasets (reference: heat/datasets/ ships iris.csv/h5/nc and
diabetes.h5 as static files for tests and examples).

This package generates equivalent small datasets on demand instead of
shipping binaries: deterministic synthetic analogs with the same shapes
((150, 4) three-class "iris-like" blobs; (442, 10) regression "diabetes-like"
data), plus writers to materialize them as CSV/HDF5 for I/O-path exercises.
"""

from __future__ import annotations

import os
from typing import Optional, Tuple

import numpy as np

from ..core import factories
from ..core.dndarray import DNDarray

__all__ = ["iris_like", "diabetes_like", "materialize"]

_IRIS_CENTERS = np.array(
    [
        [5.0, 3.4, 1.5, 0.25],
        [5.9, 2.8, 4.3, 1.3],
        [6.6, 3.0, 5.6, 2.0],
    ],
    dtype=np.float32,
)
_IRIS_STD = np.array([0.35, 0.35, 0.3, 0.2], dtype=np.float32)


def iris_like(split: Optional[int] = None, return_labels: bool = False):
    """A deterministic (150, 4) three-class dataset with iris-like cluster
    geometry, for estimator convergence tests (stand-in for the reference's
    heat/datasets/iris.h5)."""
    rng = np.random.default_rng(1234)
    xs, ys = [], []
    for i, c in enumerate(_IRIS_CENTERS):
        xs.append(rng.normal(c, _IRIS_STD, size=(50, 4)).astype(np.float32))
        ys.append(np.full(50, i, dtype=np.int32))
    x = np.concatenate(xs)
    y = np.concatenate(ys)
    data = factories.array(x, split=split)
    if return_labels:
        return data, factories.array(y, split=split)
    return data


def diabetes_like(split: Optional[int] = None):
    """A deterministic (442, 10) standardized regression dataset (stand-in for
    the reference's heat/datasets/diabetes.h5)."""
    rng = np.random.default_rng(5678)
    x = rng.standard_normal((442, 10)).astype(np.float32)
    x = (x - x.mean(0)) / x.std(0)
    return factories.array(x, split=split)


def materialize(directory: str) -> dict:
    """Write the generated datasets as iris.csv/iris.h5/diabetes.h5 under
    ``directory`` and return the paths — mirrors the reference's on-disk
    layout for I/O tests and examples."""
    from ..core import io

    os.makedirs(directory, exist_ok=True)
    paths = {}
    iris = iris_like()
    iris_csv = os.path.join(directory, "iris.csv")
    io.save_csv(iris, iris_csv)
    paths["iris.csv"] = iris_csv
    if io.supports_hdf5():
        iris_h5 = os.path.join(directory, "iris.h5")
        io.save_hdf5(iris, iris_h5, "data")
        paths["iris.h5"] = iris_h5
        diabetes_h5 = os.path.join(directory, "diabetes.h5")
        io.save_hdf5(diabetes_like(), diabetes_h5, "x")
        paths["diabetes.h5"] = diabetes_h5
    return paths

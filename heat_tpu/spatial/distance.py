"""Pairwise distance computation.

TPU-native re-design of reference heat/spatial/distance.py. The reference's
``_dist`` engine rotates the smaller operand's shards around an MPI ring —
each iteration sends the stationary shard to ``(rank+i) % size``, computes one
tile, and exploits symmetry to halve the iteration count
(distance.py:265-369 symmetric, :429-487 general). That systolic schedule is
exactly ring attention's; here it is written once as a ``shard_map`` kernel
whose rotation is ``lax.ppermute`` over the mesh axis and whose tile compute
is an MXU-shaped quadratic-expansion matmul.

For the common benchmark case (one operand replicated, reference
distance.py:422-427) no ring is needed: a single sharded jnp expression
compiles to the local metric kernel.
"""

from __future__ import annotations

from typing import Callable, Optional

import functools

import jax
import jax.numpy as jnp

from ..core import factories, sanitation, types
from ..core.communication import ppermute as _ppermute
from ..core.dndarray import DNDarray, _ensure_split

__all__ = ["cdist", "manhattan", "rbf"]


# ----------------------------------------------------------------------------
# local metric kernels (reference distance.py:16-134)
# ----------------------------------------------------------------------------
def _euclidian(x: jax.Array, y: jax.Array) -> jax.Array:
    """Direct pairwise Euclidean distance (reference distance.py:16-37)."""
    diff = x[:, None, :] - y[None, :, :]
    return jnp.sqrt(jnp.sum(diff * diff, axis=-1))


def _sq_euclidian_fast(x: jax.Array, y: jax.Array) -> jax.Array:
    """Squared pairwise distance via quadratic expansion: |x|² + |y|² − 2x·yᵀ
    — one MXU matmul instead of an O(nmf) broadcast, the TPU fast path.
    Shared by cdist and the k-clustering assignment kernels."""
    xn = jnp.sum(x * x, axis=1, keepdims=True)
    yn = jnp.sum(y * y, axis=1, keepdims=True)
    return jnp.maximum(xn + yn.T - 2.0 * (x @ y.T), 0.0)


def _euclidian_fast(x: jax.Array, y: jax.Array) -> jax.Array:
    """Quadratic-expansion Euclidean distance (reference distance.py:40-60)."""
    return jnp.sqrt(_sq_euclidian_fast(x, y))


def _manhattan(x: jax.Array, y: jax.Array) -> jax.Array:
    """Pairwise L1 distance (reference distance.py:95-115)."""
    return jnp.sum(jnp.abs(x[:, None, :] - y[None, :, :]), axis=-1)


def _gaussian(x: jax.Array, y: jax.Array, sigma: float = 1.0) -> jax.Array:
    """RBF kernel values (reference distance.py:63-92)."""
    d2 = jnp.sum((x[:, None, :] - y[None, :, :]) ** 2, axis=-1)
    return jnp.exp(-d2 / (2.0 * sigma * sigma))


def _gaussian_fast(x: jax.Array, y: jax.Array, sigma: float = 1.0) -> jax.Array:
    """RBF via quadratic expansion (reference distance.py:118-134)."""
    return jnp.exp(-_sq_euclidian_fast(x, y) / (2.0 * sigma * sigma))


def cdist(X: DNDarray, Y: Optional[DNDarray] = None, quadratic_expansion: bool = False) -> DNDarray:
    """Pairwise distance matrix (reference distance.py:136-175)."""
    metric = _euclidian_fast if quadratic_expansion else _euclidian
    return _dist(X, Y, metric)


def manhattan(X: DNDarray, Y: Optional[DNDarray] = None, expand: bool = False) -> DNDarray:
    """Pairwise L1 distance matrix (reference distance.py:176-207)."""
    return _dist(X, Y, _manhattan)


@functools.lru_cache(maxsize=32)
def _gaussian_metric(sigma: float, fast: bool) -> Callable:
    """One stable metric closure per (sigma, fast) — a fresh lambda per rbf
    call would defeat the ring-program caches keyed on the metric object."""
    if fast:
        return lambda x, y: _gaussian_fast(x, y, sigma)
    return lambda x, y: _gaussian(x, y, sigma)


def rbf(
    X: DNDarray,
    Y: Optional[DNDarray] = None,
    sigma: float = 1.0,
    quadratic_expansion: bool = False,
) -> DNDarray:
    """Pairwise RBF kernel matrix (reference distance.py:176-207)."""
    return _dist(X, Y, _gaussian_metric(float(sigma), bool(quadratic_expansion)))


def _dist(X: DNDarray, Y: Optional[DNDarray], metric: Callable) -> DNDarray:
    """Distance engine (reference distance.py:209-487)."""
    sanitation.sanitize_in(X)
    if X.ndim != 2:
        raise NotImplementedError(f"X should be 2D, but was {X.ndim}D")
    promoted = types.promote_types(X.dtype, types.float32)
    xl = X.larray.astype(promoted.jax_type())

    if Y is None or Y is X:
        yl, y_split, y_obj = xl, X.split, X
    else:
        sanitation.sanitize_in(Y)
        if Y.ndim != 2:
            raise NotImplementedError(f"Y should be 2D, but was {Y.ndim}D")
        if X.shape[1] != Y.shape[1]:
            raise ValueError("inputs must have the same number of features")
        promoted = types.promote_types(promoted, Y.dtype)
        xl = xl.astype(promoted.jax_type())
        yl = Y.larray.astype(promoted.jax_type())
        y_split, y_obj = Y.split, Y

    comm = X.comm
    n, m = xl.shape[0], yl.shape[0]
    p = comm.size

    use_ring = X.split == 0 and y_split == 0 and p > 1
    if use_ring:
        symmetric = Y is None or Y is X
        # ragged row counts: pad to the next multiple of p and slice the
        # result — the reference's *v collectives have no XLA analog
        # (SURVEY.md §7), pad+mask is the balanced-only rendering
        n_pad, m_pad = (-n) % p, (-m) % p
        if symmetric and n_pad:
            xl = yl = jnp.pad(xl, ((0, n_pad), (0, 0)))
        elif not symmetric:
            if n_pad:
                xl = jnp.pad(xl, ((0, n_pad), (0, 0)))
            if m_pad:
                yl = jnp.pad(yl, ((0, m_pad), (0, 0)))
        xl = _ensure_split(xl, 0, comm)
        yl = xl if symmetric else _ensure_split(yl, 0, comm)
        if symmetric:
            result = _ring_dist_sym(xl, metric, comm)
        else:
            result = _ring_dist(xl, yl, metric, comm)
        if n_pad or m_pad:
            result = result[:n, :m]
    else:
        # one operand replicated (reference distance.py:422-427) — or a layout
        # the ring does not cover: a single sharded expression, XLA schedules it
        result = metric(xl, yl)

    split = 0 if X.split == 0 else None
    result = _ensure_split(result, split, comm)
    return DNDarray(
        result, tuple(result.shape), types.canonical_heat_type(result.dtype), split, X.device, comm
    )


def _sym_schedule(p: int):
    """Rotation schedule of the symmetric ring: step offsets whose tiles are
    computed directly; offsets p-i for i in the first half arrive as
    transposes. ``(paired, self_paired)`` — ``len(paired) (+1 if
    self_paired)`` rotations instead of the general ring's p-1 (the
    reference's symmetry halving, distance.py:272-327)."""
    paired = list(range(1, (p - 1) // 2 + 1))
    self_paired = p % 2 == 0 and p > 1
    return paired, self_paired


def _ring_dist_sym(xl: jax.Array, metric: Callable, comm) -> jax.Array:
    """Symmetric systolic ring (Y ≡ X): compute only the upper half of the
    tile offsets and mirror each tile to its transpose owner — ⌈p/2⌉
    rotations of the stationary operand instead of p−1, recovering the
    reference's symmetry optimization (reference distance.py:272-327) with
    the mirrored tile travelling over the same ICI ring."""
    return _sym_program(comm.mesh, comm.axis_name, comm.size, metric)(xl)


@functools.lru_cache(maxsize=64)
def _sym_program(mesh, axis: str, p: int, metric: Callable):
    """Cached jitted symmetric-ring program (one trace per (mesh, metric);
    jit re-specializes per operand shape internally). Exposed so tests can
    ``.lower()`` it for HLO collective-budget assertions."""
    from jax.sharding import PartitionSpec as P

    paired, self_paired = _sym_schedule(p)

    h = len(paired)  # offsets 1..h computed directly; their mirrors arrive

    def kernel(xs):
        m_block = xs.shape[0]  # per-device row block
        rank = jax.lax.axis_index(axis)

        def write(out, tile, col_block):
            col = (col_block % p) * m_block
            return jax.lax.dynamic_update_slice(
                out, tile, (jnp.zeros((), col.dtype), col)
            )

        out = jnp.zeros((xs.shape[0], m_block * p), dtype=xs.dtype)
        try:
            out = jax.lax.pcast(out, (axis,), to="varying")
        except (AttributeError, TypeError):  # pragma: no cover - older jax
            pass
        # diagonal tile: local compute, no communication
        out = write(out, metric(xs, xs), rank)

        # ⌈p/2⌉ uniform shift-1 rotations in a fori_loop (program size O(1)
        # in p — tests/test_mesh64_compile); each step stashes its tile at
        # slot (rank+i) % p so ONE all_to_all afterwards hands every device
        # exactly the mirror tiles of its row, replacing the per-step
        # variable-shift ppermute the unrolled schedule needed
        buf0 = jnp.zeros((p, m_block, m_block), dtype=xs.dtype)

        def step(i, carry):
            ys_cur, out, buf = carry
            ys_cur = _ppermute(ys_cur, axis, p, shift=1)  # now holds shard rank+i
            tile = metric(xs, ys_cur)  # tile (rank, rank+i)
            out = write(out, tile, rank + i)
            slot = (rank + i) % p
            buf = jax.lax.dynamic_update_slice(
                buf, tile[None], (slot, jnp.zeros((), slot.dtype), jnp.zeros((), slot.dtype))
            )
            return ys_cur, out, buf

        ys_cur, out, buf = jax.lax.fori_loop(1, h + 1, step, (xs, out, buf0))

        if h:
            # slot j of device d holds tile (d, j) iff (j - d) % p in 1..h;
            # all_to_all delivers slot j to device j — device r receives
            # tile (d, r) from every d, i.e. its whole mirror column
            mirror = jax.lax.all_to_all(buf, axis, split_axis=0, concat_axis=0)

            def fold_mirror(d, out):
                valid = ((rank - d) % p >= 1) & ((rank - d) % p <= h)
                col = (d % p) * m_block
                cur = jax.lax.dynamic_slice(
                    out, (jnp.zeros((), col.dtype), col), (m_block, m_block)
                )
                tile_t = mirror[d].T
                return jax.lax.dynamic_update_slice(
                    out, jnp.where(valid, tile_t, cur), (jnp.zeros((), col.dtype), col)
                )

            out = jax.lax.fori_loop(0, p, fold_mirror, out)

        if self_paired:
            # p even: offset p/2 is its own mirror — every device computes it
            ys_cur = _ppermute(ys_cur, axis, p, shift=1)
            out = write(out, metric(xs, ys_cur), rank + p // 2)
        return out

    return jax.jit(
        jax.shard_map(
            kernel,
            mesh=mesh,
            in_specs=P(axis, None),
            out_specs=P(axis, None),
            check_vma=False,
        )
    )


def _ring_dist(xl: jax.Array, yl: jax.Array, metric: Callable, comm) -> jax.Array:
    """Systolic ring: the stationary X shard computes one tile per step while
    Y shards rotate via ppermute (the reference's Send-to-(rank+i) schedule,
    distance.py:272-327, re-expressed as a collective-permute ring)."""
    return _ring_program(comm.mesh, comm.axis_name, comm.size, metric)(xl, yl)


@functools.lru_cache(maxsize=64)
def _ring_program(mesh, axis: str, p: int, metric: Callable):
    """Cached jitted general-ring program (one trace per (mesh, metric))."""
    from jax.sharding import PartitionSpec as P

    def kernel(xs, ys):
        m_block = ys.shape[0]  # per-device row block of the rotating operand
        rank = jax.lax.axis_index(axis)

        def fold(i, ys_cur, out):
            # ys_cur currently holds the shard of device (rank + i) % p
            tile = metric(xs, ys_cur)
            col = ((rank + i.astype(rank.dtype)) % p) * m_block
            return jax.lax.dynamic_update_slice(out, tile, (jnp.zeros((), col.dtype), col))

        def body(i, carry):
            ys_cur, out = carry
            out = fold(i, ys_cur, out)
            # rotate: receive the next shard from the right neighbor
            ys_next = _ppermute(ys_cur, axis, p, shift=1)
            return ys_next, out

        out0 = jax.lax.pcast(
            jnp.zeros((xs.shape[0], m_block * p), dtype=xs.dtype), (axis,), to="varying"
        )
        # p-1 rotations; the last visiting shard is folded without re-sending it
        ys_last, out = jax.lax.fori_loop(0, p - 1, body, (ys, out0))
        return fold(jnp.asarray(p - 1), ys_last, out)

    return jax.jit(
        jax.shard_map(
            kernel,
            mesh=mesh,
            in_specs=(P(axis, None), P(axis, None)),
            out_specs=P(axis, None),
        )
    )

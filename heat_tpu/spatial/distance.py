"""Pairwise distance computation.

TPU-native re-design of reference heat/spatial/distance.py. The reference's
``_dist`` engine rotates the smaller operand's shards around an MPI ring —
each iteration sends the stationary shard to ``(rank+i) % size``, computes one
tile, and exploits symmetry to halve the iteration count
(distance.py:265-369 symmetric, :429-487 general). That systolic schedule is
exactly ring attention's; here it is written once as a ``shard_map`` kernel
whose rotation is ``lax.ppermute`` over the mesh axis and whose tile compute
is an MXU-shaped quadratic-expansion matmul.

For the common benchmark case (one operand replicated, reference
distance.py:422-427) no ring is needed: a single sharded jnp expression
compiles to the local metric kernel.
"""

from __future__ import annotations

from typing import Callable, Optional

import jax
import jax.numpy as jnp

from ..core import factories, sanitation, types
from ..core.dndarray import DNDarray, _ensure_split

__all__ = ["cdist", "manhattan", "rbf"]


# ----------------------------------------------------------------------------
# local metric kernels (reference distance.py:16-134)
# ----------------------------------------------------------------------------
def _euclidian(x: jax.Array, y: jax.Array) -> jax.Array:
    """Direct pairwise Euclidean distance (reference distance.py:16-37)."""
    diff = x[:, None, :] - y[None, :, :]
    return jnp.sqrt(jnp.sum(diff * diff, axis=-1))


def _sq_euclidian_fast(x: jax.Array, y: jax.Array) -> jax.Array:
    """Squared pairwise distance via quadratic expansion: |x|² + |y|² − 2x·yᵀ
    — one MXU matmul instead of an O(nmf) broadcast, the TPU fast path.
    Shared by cdist and the k-clustering assignment kernels."""
    xn = jnp.sum(x * x, axis=1, keepdims=True)
    yn = jnp.sum(y * y, axis=1, keepdims=True)
    return jnp.maximum(xn + yn.T - 2.0 * (x @ y.T), 0.0)


def _euclidian_fast(x: jax.Array, y: jax.Array) -> jax.Array:
    """Quadratic-expansion Euclidean distance (reference distance.py:40-60)."""
    return jnp.sqrt(_sq_euclidian_fast(x, y))


def _manhattan(x: jax.Array, y: jax.Array) -> jax.Array:
    """Pairwise L1 distance (reference distance.py:95-115)."""
    return jnp.sum(jnp.abs(x[:, None, :] - y[None, :, :]), axis=-1)


def _gaussian(x: jax.Array, y: jax.Array, sigma: float = 1.0) -> jax.Array:
    """RBF kernel values (reference distance.py:63-92)."""
    d2 = jnp.sum((x[:, None, :] - y[None, :, :]) ** 2, axis=-1)
    return jnp.exp(-d2 / (2.0 * sigma * sigma))


def _gaussian_fast(x: jax.Array, y: jax.Array, sigma: float = 1.0) -> jax.Array:
    """RBF via quadratic expansion (reference distance.py:118-134)."""
    return jnp.exp(-_sq_euclidian_fast(x, y) / (2.0 * sigma * sigma))


def cdist(X: DNDarray, Y: Optional[DNDarray] = None, quadratic_expansion: bool = False) -> DNDarray:
    """Pairwise distance matrix (reference distance.py:136-175)."""
    metric = _euclidian_fast if quadratic_expansion else _euclidian
    return _dist(X, Y, metric)


def manhattan(X: DNDarray, Y: Optional[DNDarray] = None, expand: bool = False) -> DNDarray:
    """Pairwise L1 distance matrix (reference distance.py:176-207)."""
    return _dist(X, Y, _manhattan)


def rbf(
    X: DNDarray,
    Y: Optional[DNDarray] = None,
    sigma: float = 1.0,
    quadratic_expansion: bool = False,
) -> DNDarray:
    """Pairwise RBF kernel matrix (reference distance.py:176-207)."""
    if quadratic_expansion:
        return _dist(X, Y, lambda x, y: _gaussian_fast(x, y, sigma))
    return _dist(X, Y, lambda x, y: _gaussian(x, y, sigma))


def _dist(X: DNDarray, Y: Optional[DNDarray], metric: Callable) -> DNDarray:
    """Distance engine (reference distance.py:209-487)."""
    sanitation.sanitize_in(X)
    if X.ndim != 2:
        raise NotImplementedError(f"X should be 2D, but was {X.ndim}D")
    promoted = types.promote_types(X.dtype, types.float32)
    xl = X.larray.astype(promoted.jax_type())

    if Y is None or Y is X:
        yl, y_split, y_obj = xl, X.split, X
    else:
        sanitation.sanitize_in(Y)
        if Y.ndim != 2:
            raise NotImplementedError(f"Y should be 2D, but was {Y.ndim}D")
        if X.shape[1] != Y.shape[1]:
            raise ValueError("inputs must have the same number of features")
        promoted = types.promote_types(promoted, Y.dtype)
        xl = xl.astype(promoted.jax_type())
        yl = Y.larray.astype(promoted.jax_type())
        y_split, y_obj = Y.split, Y

    comm = X.comm
    n, m = xl.shape[0], yl.shape[0]
    p = comm.size

    use_ring = (
        X.split == 0
        and y_split == 0
        and p > 1
        and n % p == 0
        and m % p == 0
    )
    if use_ring:
        result = _ring_dist(xl, yl, metric, comm)
    else:
        # one operand replicated (reference distance.py:422-427) — or a layout
        # the ring does not cover: a single sharded expression, XLA schedules it
        result = metric(xl, yl)

    split = 0 if X.split == 0 else None
    result = _ensure_split(result, split, comm)
    return DNDarray(
        result, tuple(result.shape), types.canonical_heat_type(result.dtype), split, X.device, comm
    )


def _ring_dist(xl: jax.Array, yl: jax.Array, metric: Callable, comm) -> jax.Array:
    """Systolic ring: the stationary X shard computes one tile per step while
    Y shards rotate via ppermute (the reference's Send-to-(rank+i) schedule,
    distance.py:272-327, re-expressed as a collective-permute ring)."""
    from jax.sharding import PartitionSpec as P

    p = comm.size
    axis = comm.axis_name
    m_block = yl.shape[0] // p

    def kernel(xs, ys):
        rank = jax.lax.axis_index(axis)

        def fold(i, ys_cur, out):
            # ys_cur currently holds the shard of device (rank + i) % p
            tile = metric(xs, ys_cur)
            col = ((rank + i.astype(rank.dtype)) % p) * m_block
            return jax.lax.dynamic_update_slice(out, tile, (jnp.zeros((), col.dtype), col))

        def body(i, carry):
            ys_cur, out = carry
            out = fold(i, ys_cur, out)
            # rotate: receive the next shard from the right neighbor
            ys_next = jax.lax.ppermute(
                ys_cur, axis, [(j, (j - 1) % p) for j in range(p)]
            )
            return ys_next, out

        out0 = jax.lax.pcast(
            jnp.zeros((xs.shape[0], m_block * p), dtype=xs.dtype), (axis,), to="varying"
        )
        # p-1 rotations; the last visiting shard is folded without re-sending it
        ys_last, out = jax.lax.fori_loop(0, p - 1, body, (ys, out0))
        return fold(jnp.asarray(p - 1), ys_last, out)

    fn = jax.jit(
        jax.shard_map(
            kernel,
            mesh=comm.mesh,
            in_specs=(P(axis, None), P(axis, None)),
            out_specs=P(axis, None),
        )
    )
    return fn(xl, yl)

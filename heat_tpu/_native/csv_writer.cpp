// Native CSV writer for heat_tpu.
//
// Counterpart of csv_reader.cpp: the reference serializes CSV rows in Python
// with a token-ring of rank-ordered writes (reference heat/core/io.py:926-1059).
// With a single controller the ordering problem disappears; what remains is
// the formatting hot loop, which this file runs in C++ worker threads — each
// thread formats a contiguous row range into its own buffer, then the buffers
// are written to the file in order.
//
// Exposed C ABI (ctypes-bound in heat_tpu/_native/__init__.py):
//   csv_write(path, data, rows, cols, sep, decimals, append, n_threads)
//     data:     row-major double buffer (rows x cols)
//     decimals: >= 0 -> fixed "%.<d>f"; < 0 -> shortest round-trip "%.17g"
//     append:   nonzero appends (header lines already written by the caller)
//     returns rows written, or -1 on I/O failure
//
// Build: g++ -O3 -std=c++17 -shared -fPIC csv_reader.cpp csv_writer.cpp \
//            -o libheatcsv.so -lpthread

#include <charconv>
#include <cstdio>
#include <cstring>
#include <string>
#include <thread>
#include <vector>

namespace {

void format_rows(const double* data, long long row_begin, long long row_end,
                 long long cols, char sep, int decimals, std::string* out) {
  char num[64];
  out->reserve(static_cast<size_t>((row_end - row_begin) * cols * 12));
  for (long long r = row_begin; r < row_end; ++r) {
    const double* row = data + r * cols;
    for (long long c = 0; c < cols; ++c) {
      if (decimals >= 0) {
        size_t len = static_cast<size_t>(
            snprintf(num, sizeof(num), "%.*f", decimals, row[c]));
        if (len < sizeof(num)) {
          out->append(num, len);
        } else {
          // %.2f of 1e300 needs ~300 chars: reformat on the heap instead of
          // appending past the truncated stack buffer
          std::vector<char> wide(len + 1);
          snprintf(wide.data(), wide.size(), "%.*f", decimals, row[c]);
          out->append(wide.data(), len);
        }
      } else {
        // shortest round-trip representation — ~6x faster than %.17g and
        // produces the same value on re-parse
        auto res = std::to_chars(num, num + sizeof(num), row[c]);
        out->append(num, static_cast<size_t>(res.ptr - num));
      }
      out->push_back(c + 1 < cols ? sep : '\n');
    }
  }
}

}  // namespace

extern "C" long long csv_write(const char* path, const double* data,
                               long long rows, long long cols, char sep,
                               int decimals, int append, int n_threads) {
  if (rows < 0 || cols <= 0) return -1;
  if (n_threads < 1) n_threads = 1;
  long long max_threads = rows / 4096 + 1;  // don't spawn for tiny files
  if (n_threads > max_threads) n_threads = static_cast<int>(max_threads);

  std::vector<std::string> chunks(static_cast<size_t>(n_threads));
  std::vector<std::thread> workers;
  long long per = (rows + n_threads - 1) / n_threads;
  for (int t = 0; t < n_threads; ++t) {
    long long begin = static_cast<long long>(t) * per;
    long long end = begin + per < rows ? begin + per : rows;
    if (begin >= end) break;
    workers.emplace_back(format_rows, data, begin, end, cols, sep, decimals,
                         &chunks[static_cast<size_t>(t)]);
  }
  for (auto& w : workers) w.join();

  FILE* f = fopen(path, append ? "ab" : "wb");
  if (!f) return -1;
  for (const auto& chunk : chunks) {
    if (!chunk.empty() &&
        fwrite(chunk.data(), 1, chunk.size(), f) != chunk.size()) {
      fclose(f);
      return -1;
    }
  }
  if (fclose(f) != 0) return -1;
  return rows;
}

"""Native (C++) runtime components, bound via ctypes.

The reference delegates its native layer to libtorch + MPI (reference
SURVEY.md vital stats); the compute/communication side of this framework
delegates to XLA the same way. The host-side data path, however, is our own:
this package holds the C++ pieces, compiled on demand with the in-image g++
toolchain and loaded through ctypes (no pybind11 in the image).

Current components:
- ``csv_reader.cpp`` — multithreaded byte-range CSV parser (the native
  realization of reference heat/core/io.py:713-925's per-rank byte-range
  scheme); used by :func:`heat_tpu.core.io.load_csv` with a pure-Python
  fallback when the toolchain is unavailable.

Set ``HEAT_TPU_NO_NATIVE=1`` to disable compilation and force the fallbacks.
"""

from __future__ import annotations

import ctypes
import os
import subprocess
import threading
from typing import Optional, Tuple

import numpy as np

__all__ = ["csv_scan", "csv_parse", "csv_write", "native_available"]

_DIR = os.path.dirname(os.path.abspath(__file__))
_SRCS = [os.path.join(_DIR, "csv_reader.cpp"), os.path.join(_DIR, "csv_writer.cpp")]
_SO = os.path.join(_DIR, "libheatcsv.so")

_lock = threading.Lock()
_lib: Optional[ctypes.CDLL] = None
_tried = False


def _build() -> bool:
    cmd = [
        "g++", "-O3", "-std=c++17", "-shared", "-fPIC", *_SRCS, "-o", _SO, "-lpthread",
    ]
    try:
        res = subprocess.run(cmd, capture_output=True, timeout=120)
        return res.returncode == 0
    except (OSError, subprocess.SubprocessError, ValueError):
        return False  # no g++ / timeout / bad argv: fallbacks own the data path


def _load() -> Optional[ctypes.CDLL]:
    """Compile (once, cached as a .so next to the source) and load."""
    global _lib, _tried
    with _lock:
        if _lib is not None or _tried:
            return _lib
        _tried = True
        if os.environ.get("HEAT_TPU_NO_NATIVE"):
            return None
        if not os.path.exists(_SO) or any(
            os.path.getmtime(_SO) < os.path.getmtime(src) for src in _SRCS
        ):
            if not _build():
                return None
        try:
            lib = ctypes.CDLL(_SO)
        except OSError:
            return None
        lib.csv_scan.argtypes = [
            ctypes.c_char_p, ctypes.c_char, ctypes.c_longlong,
            ctypes.POINTER(ctypes.c_longlong), ctypes.POINTER(ctypes.c_longlong),
        ]
        lib.csv_scan.restype = ctypes.c_int
        lib.csv_parse.argtypes = [
            ctypes.c_char_p, ctypes.c_char, ctypes.c_longlong, ctypes.c_longlong,
            ctypes.c_longlong, ctypes.POINTER(ctypes.c_double), ctypes.c_int,
        ]
        lib.csv_parse.restype = ctypes.c_longlong
        lib.csv_write.argtypes = [
            ctypes.c_char_p, ctypes.POINTER(ctypes.c_double), ctypes.c_longlong,
            ctypes.c_longlong, ctypes.c_char, ctypes.c_int, ctypes.c_int, ctypes.c_int,
        ]
        lib.csv_write.restype = ctypes.c_longlong
        _lib = lib
        return _lib


def native_available() -> bool:
    """Whether the native CSV reader could be compiled/loaded here."""
    return _load() is not None


def csv_scan(path: str, sep: str = ",", skip_lines: int = 0) -> Tuple[int, int]:
    """(rows, cols) of the data region of a CSV file. Raises on failure."""
    lib = _load()
    if lib is None:
        raise RuntimeError("native CSV reader unavailable")
    rows = ctypes.c_longlong(0)
    cols = ctypes.c_longlong(0)
    rc = lib.csv_scan(
        path.encode(), sep.encode()[:1], skip_lines, ctypes.byref(rows), ctypes.byref(cols)
    )
    if rc == -1:
        raise IOError(f"cannot read {path}")
    if rc == -2:
        return 0, 0
    return int(rows.value), int(cols.value)


def csv_parse(
    path: str, sep: str = ",", skip_lines: int = 0, n_threads: Optional[int] = None
) -> np.ndarray:
    """Parse a CSV file to a (rows, cols) float64 array with C++ threads."""
    rows, cols = csv_scan(path, sep, skip_lines)
    out = np.empty((rows, cols), dtype=np.float64)
    if rows == 0:
        return out
    lib = _load()
    assert lib is not None
    nt = n_threads or min(os.cpu_count() or 1, 16)
    done = lib.csv_parse(
        path.encode(), sep.encode()[:1], skip_lines, rows, cols,
        out.ctypes.data_as(ctypes.POINTER(ctypes.c_double)), nt,
    )
    if done != rows:
        raise ValueError(f"malformed CSV {path}: parsed {done} of {rows} rows")
    return out


def csv_write(
    path: str,
    data: np.ndarray,
    sep: str = ",",
    decimals: int = -1,
    append: bool = False,
    n_threads: Optional[int] = None,
) -> int:
    """Write a 2-D float array as CSV with C++ formatting threads.

    ``decimals < 0`` writes shortest-round-trip (%.17g) values; ``append``
    adds to an existing file (used after Python writes header lines).
    """
    lib = _load()
    if lib is None:
        raise RuntimeError("native CSV writer unavailable")
    arr = np.ascontiguousarray(data, dtype=np.float64)
    if arr.ndim != 2:
        raise ValueError(f"need a 2-D array, got {arr.ndim}-D")
    nt = n_threads or min(os.cpu_count() or 1, 16)
    done = lib.csv_write(
        path.encode(), arr.ctypes.data_as(ctypes.POINTER(ctypes.c_double)),
        arr.shape[0], arr.shape[1], sep.encode()[:1], decimals,
        1 if append else 0, nt,
    )
    if done != arr.shape[0]:
        raise IOError(f"native CSV write to {path} failed")
    return int(done)

// Native CSV reader for heat_tpu.
//
// The reference framework reads CSV by splitting the file into per-rank byte
// ranges aligned to line breaks and parsing each range in Python
// (reference heat/core/io.py:713-925). This is the native equivalent of that
// data-loader: the byte-range decomposition is kept, but ranges are parsed by
// C++ worker threads (strtod hot loop, no per-line Python objects), feeding
// one contiguous output buffer that the caller hands to jax.device_put.
//
// Exposed C ABI (ctypes-bound in heat_tpu/_native/__init__.py):
//   csv_scan(path, sep, skip_lines, &rows, &cols)  -> 0 on success
//   csv_parse(path, sep, skip_lines, rows, cols, out, n_threads) -> rows done
//
// Build: g++ -O3 -std=c++17 -shared -fPIC csv_reader.cpp -o libheatcsv.so -lpthread

#include <charconv>
#include <cstdio>
#include <cstdlib>
#include <cstring>
#include <fstream>
#include <string>
#include <thread>
#include <vector>

namespace {

// Read the whole file into memory. Returns false on IO failure.
bool slurp(const char* path, std::string& buf) {
  std::ifstream f(path, std::ios::binary | std::ios::ate);
  if (!f) return false;
  std::streamsize size = f.tellg();
  if (size < 0) return false;
  f.seekg(0);
  buf.resize(static_cast<size_t>(size));
  return size == 0 || static_cast<bool>(f.read(&buf[0], size));
}

// Offset of the first byte after `skip_lines` newlines.
size_t skip_offset(const std::string& buf, long long skip_lines) {
  size_t pos = 0;
  for (long long i = 0; i < skip_lines && pos < buf.size(); ++i) {
    const char* nl = static_cast<const char*>(memchr(buf.data() + pos, '\n', buf.size() - pos));
    if (!nl) return buf.size();
    pos = static_cast<size_t>(nl - buf.data()) + 1;
  }
  return pos;
}

// A line is "data" if it contains any non-whitespace character.
inline bool is_data_line(const char* begin, const char* end) {
  for (const char* p = begin; p < end; ++p) {
    if (*p != ' ' && *p != '\t' && *p != '\r') return true;
  }
  return false;
}

// Count data lines in [begin, end); the final line may lack a newline.
long long count_lines(const char* begin, const char* end) {
  long long n = 0;
  const char* p = begin;
  while (p < end) {
    const char* nl = static_cast<const char*>(memchr(p, '\n', static_cast<size_t>(end - p)));
    const char* line_end = nl ? nl : end;
    if (is_data_line(p, line_end)) ++n;
    p = nl ? nl + 1 : end;
  }
  return n;
}

// Parse data lines of [begin, end) into out[row0 * cols ...].
// Returns rows parsed, or -1 on malformed input (wrong column count).
long long parse_range(const char* begin, const char* end, char sep, long long cols,
                      double* out, long long row0) {
  long long row = row0;
  const char* p = begin;
  while (p < end) {
    const char* nl = static_cast<const char*>(memchr(p, '\n', static_cast<size_t>(end - p)));
    const char* line_end = nl ? nl : end;
    if (is_data_line(p, line_end)) {
      double* dst = out + row * cols;
      const char* q = p;
      for (long long c = 0; c < cols; ++c) {
        while (q < line_end && *q != sep && (*q == ' ' || *q == '\t' || *q == '\r')) ++q;
        if (q < line_end && *q == '+') ++q;  // from_chars rejects leading '+'
        // from_chars: ~4x strtod, locale-free, and bounded by line_end so a
        // short row cannot silently consume the next line
        double val;
        std::from_chars_result res = std::from_chars(q, line_end, val);
        if (res.ec != std::errc()) return -1;
        dst[c] = val;
        q = res.ptr;
        // consume whitespace that is not itself the separator
        while (q < line_end && *q != sep && (*q == ' ' || *q == '\t' || *q == '\r')) ++q;
        if (c + 1 < cols) {
          if (q >= line_end || *q != sep) return -1;
          ++q;
        }
      }
      // a ragged row with MORE fields than the first data row must fail,
      // not silently truncate
      if (q < line_end && (*q == sep || is_data_line(q, line_end))) return -1;
      ++row;
    }
    p = nl ? nl + 1 : end;
  }
  return row - row0;
}

// Split [begin, end) into n newline-aligned chunks.
std::vector<const char*> chunk_bounds(const char* begin, const char* end, int n) {
  std::vector<const char*> bounds;
  bounds.push_back(begin);
  size_t total = static_cast<size_t>(end - begin);
  for (int i = 1; i < n; ++i) {
    const char* target = begin + total * i / n;
    if (target <= bounds.back()) target = bounds.back();
    const char* nl = static_cast<const char*>(
        memchr(target, '\n', static_cast<size_t>(end - target)));
    bounds.push_back(nl ? nl + 1 : end);
  }
  bounds.push_back(end);
  return bounds;
}

}  // namespace

extern "C" {

// Scan shape: rows = data lines after skip, cols from the first data line.
// Returns 0 on success, -1 on IO error, -2 on empty file.
int csv_scan(const char* path, char sep, long long skip_lines, long long* out_rows,
             long long* out_cols) {
  std::string buf;
  if (!slurp(path, buf)) return -1;
  size_t start = skip_offset(buf, skip_lines);
  const char* begin = buf.data() + start;
  const char* end = buf.data() + buf.size();
  *out_rows = count_lines(begin, end);
  if (*out_rows == 0) {
    *out_cols = 0;
    return -2;
  }
  // columns of the first data line: separators outside numbers + 1
  const char* p = begin;
  while (p < end) {
    const char* nl = static_cast<const char*>(memchr(p, '\n', static_cast<size_t>(end - p)));
    const char* line_end = nl ? nl : end;
    if (is_data_line(p, line_end)) {
      long long cols = 1;
      for (const char* q = p; q < line_end; ++q) {
        if (*q == sep) ++cols;
      }
      *out_cols = cols;
      return 0;
    }
    p = nl ? nl + 1 : end;
  }
  return -2;
}

// Parse the file into out (rows*cols doubles, preallocated by the caller).
// Returns rows parsed, or negative on error (-1 IO, -3 malformed).
long long csv_parse(const char* path, char sep, long long skip_lines, long long rows,
                    long long cols, double* out, int n_threads) {
  std::string buf;
  if (!slurp(path, buf)) return -1;
  size_t start = skip_offset(buf, skip_lines);
  const char* begin = buf.data() + start;
  const char* end = buf.data() + buf.size();

  if (n_threads < 1) n_threads = 1;
  std::vector<const char*> bounds = chunk_bounds(begin, end, n_threads);

  // pass 1 (parallel): rows per chunk -> starting row of each chunk
  std::vector<long long> chunk_rows(static_cast<size_t>(n_threads), 0);
  {
    std::vector<std::thread> ts;
    for (int i = 0; i < n_threads; ++i) {
      ts.emplace_back([&, i] { chunk_rows[i] = count_lines(bounds[i], bounds[i + 1]); });
    }
    for (auto& t : ts) t.join();
  }
  std::vector<long long> row0(static_cast<size_t>(n_threads) + 1, 0);
  for (int i = 0; i < n_threads; ++i) row0[i + 1] = row0[i] + chunk_rows[i];
  if (row0[n_threads] != rows) return -3;

  // pass 2 (parallel): parse each chunk into its row range
  std::vector<long long> done(static_cast<size_t>(n_threads), 0);
  {
    std::vector<std::thread> ts;
    for (int i = 0; i < n_threads; ++i) {
      ts.emplace_back([&, i] {
        done[i] = parse_range(bounds[i], bounds[i + 1], sep, cols, out, row0[i]);
      });
    }
    for (auto& t : ts) t.join();
  }
  long long total = 0;
  for (int i = 0; i < n_threads; ++i) {
    if (done[i] < 0) return -3;
    total += done[i];
  }
  return total;
}

}  // extern "C"

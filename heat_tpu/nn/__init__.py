"""Neural network stack.

The reference re-exports ``torch.nn`` attributes dynamically and adds
``DataParallel`` (reference heat/nn/__init__.py:19-31). The TPU-native module
library is flax.linen, re-exported here the same way: ``heat_tpu.nn.Dense``,
``heat_tpu.nn.Conv``, ``heat_tpu.nn.relu``... resolve to flax.linen, while
``DataParallel``/``DataParallelMultiGPU`` and the model zoo are native.

Note: the explicit exports below take precedence over the flax.linen shim —
in particular ``MultiHeadAttention`` and ``dot_product_attention`` are the
native sequence-parallel implementations from :mod:`heat_tpu.nn.attention`
(different signatures from flax's: no bias/dropout/decode arguments; the
ring/ulysses backends take a ``comm``).
"""

from . import attention, functional, models
from .attention import (
    MultiHeadAttention,
    dot_product_attention,
    flash_attention,
    ring_attention,
    ulysses_attention,
)
from .data_parallel import DataParallel, DataParallelMultiGPU
from .models import (
    MLP,
    ResNet,
    ResNet18,
    ResNet50,
    SimpleCNN,
    TransformerBlock,
    TransformerLM,
)

import flax.linen as _linen

__all__ = [
    "DataParallel",
    "DataParallelMultiGPU",
    "MLP",
    "SimpleCNN",
    "ResNet",
    "ResNet18",
    "ResNet50",
    "TransformerBlock",
    "TransformerLM",
    "models",
    "attention",
    "MultiHeadAttention",
    "dot_product_attention",
    "flash_attention",
    "ring_attention",
    "ulysses_attention",
]


def __getattr__(name):
    # dynamic fallback to the backing NN library, mirroring the reference's
    # torch.nn shim (heat/nn/__init__.py:19-31)
    try:
        return getattr(_linen, name)
    except AttributeError:
        raise AttributeError(f"module 'heat_tpu.nn' has no attribute {name!r}")

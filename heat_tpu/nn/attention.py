"""Sequence-parallel attention: dense, blockwise (flash-style), ring, Ulysses.

The reference framework has no attention/sequence dimension (SURVEY.md §2.3:
TP/PP/EP/Ulysses "absent"), but it owns the *mechanisms* long-context
attention is made of: the systolic ring of ``spatial/distance.py:265-369``
(rotate the moving operand with Send-to-(rank+i), compute one tile per step)
and the Alltoall axis re-sharding of ``manipulations.py:3329-3425``. This
module makes those mechanisms first-class for the long-context case:

* :func:`dot_product_attention` — dense softmax attention, the oracle.
* :func:`flash_attention` — blockwise online-softmax attention expressed as a
  ``lax.scan`` over key/value tiles. O(seq) memory instead of O(seq²); XLA
  fuses each tile into MXU matmuls. (A hand-tiled pallas kernel for the same
  math lives in :mod:`heat_tpu.ops.flash`.)
* :func:`ring_attention` — sequence parallelism over the device mesh: Q stays
  resident, K/V shards rotate via ``lax.ppermute`` (exactly the reference's
  ring cdist schedule), each step folding one tile into the online-softmax
  accumulator. Communication rides ICI; memory per chip is O(seq/p).
* :func:`ulysses_attention` — all-to-all sequence parallelism: ``lax.
  all_to_all`` re-shards [B, S/p, H, D] → [B, S, H/p, D], runs dense/blockwise
  attention per local head group, and re-shards back (the Ulysses layout
  switch; the reference's analogous axis-changing resplit is
  communication.py:336-437).

All functions take [batch, seq, heads, head_dim] arrays (flax convention) and
accumulate the softmax in float32 regardless of input dtype (bfloat16 inputs
stay bfloat16 on the matmul operands — MXU-friendly — while m/l/o run f32).
"""

from __future__ import annotations

import functools
import math
from typing import Callable, Optional

import jax
import jax.numpy as jnp
from jax.sharding import PartitionSpec as P

from ..core.communication import MeshCommunication, sanitize_comm

__all__ = [
    "dot_product_attention",
    "flash_attention",
    "ring_attention",
    "ulysses_attention",
    "MultiHeadAttention",
]


def _acc_dtype(dtype) -> jnp.dtype:
    """float32 accumulation, widened to f64 only if the inputs already are."""
    return jnp.promote_types(dtype, jnp.float32)


def dot_product_attention(
    q: jax.Array,
    k: jax.Array,
    v: jax.Array,
    *,
    causal: bool = False,
    scale: Optional[float] = None,
    mask: Optional[jax.Array] = None,
) -> jax.Array:
    """Dense softmax attention (the oracle the parallel paths are tested against).

    Parameters
    ----------
    q, k, v : jax.Array
        [batch, seq, heads, head_dim] (k/v may have a different seq length).
    causal : bool
        Lower-triangular masking (query i attends to keys ≤ i).
    scale : float, optional
        Score scale; default ``1/sqrt(head_dim)``.
    mask : jax.Array, optional
        Boolean, broadcastable to [batch, q_len, heads, k_len]; True = keep.
    """
    acc = _acc_dtype(q.dtype)
    if scale is None:
        scale = 1.0 / math.sqrt(q.shape[-1])
    s = jnp.einsum("bqhd,bkhd->bqhk", q, k).astype(acc) * scale
    if causal:
        q_ids = jnp.arange(q.shape[1])
        k_ids = jnp.arange(k.shape[1])
        cm = (q_ids[:, None] >= k_ids[None, :])[None, :, None, :]
        s = jnp.where(cm, s, -jnp.inf)
    if mask is not None:
        s = jnp.where(mask, s, -jnp.inf)
    p = jax.nn.softmax(s, axis=-1)
    return jnp.einsum("bqhk,bkhd->bqhd", p.astype(v.dtype), v)


def _tile_update(q, k_blk, v_blk, m, l, o, q_idx0, k_idx0, causal, scale, kv_valid=None):
    """Fold one K/V tile into the online-softmax state (m, l, o).

    m: [B, sq, H] running max (f32); l: [B, sq, H] running sum; o: [B, sq, H, D]
    unnormalized output. q_idx0/k_idx0 are the global sequence offsets of the
    tiles, so causal masking is correct regardless of which shard is visiting.
    ``kv_valid`` (optional, [bk] bool) masks out padded key positions.
    """
    acc = m.dtype
    s = jnp.einsum("bqhd,bkhd->bqhk", q, k_blk).astype(acc) * scale
    k_ids = k_idx0 + jnp.arange(k_blk.shape[1])
    keep = None
    if causal:
        q_ids = q_idx0 + jnp.arange(q.shape[1])
        keep = (q_ids[:, None] >= k_ids[None, :])[None, :, None, :]
    if kv_valid is not None:
        kv = kv_valid[None, None, None, :]
        keep = kv if keep is None else keep & kv
    if keep is not None:
        s = jnp.where(keep, s, -jnp.inf)
    m_new = jnp.maximum(m, s.max(axis=-1))
    # A fully-masked history has m_new = -inf; shift by 0 there so exp() is 0,
    # not NaN (the final division is guarded the same way).
    m_safe = jnp.where(jnp.isneginf(m_new), jnp.zeros((), acc), m_new)
    p = jnp.exp(s - m_safe[..., None])
    alpha = jnp.exp(m - m_safe)  # m = -inf -> 0: no prior mass
    l_new = alpha * l + p.sum(axis=-1)
    o_new = alpha[..., None] * o + jnp.einsum("bqhk,bkhd->bqhd", p, v_blk.astype(acc))
    return m_new, l_new, o_new


def _finalize(l, o, dtype):
    denom = jnp.where(l > 0, l, jnp.ones((), l.dtype))
    return (o / denom[..., None]).astype(dtype)


@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4))
def _flash_pallas_diff(q, k, v, causal, scale):
    from ..ops.flash import flash_attention_tpu

    return flash_attention_tpu(q, k, v, causal=causal, scale=scale)


def _flash_pallas_fwd(q, k, v, causal, scale):
    return _flash_pallas_diff(q, k, v, causal, scale), (q, k, v)


def _flash_pallas_bwd(causal, scale, res, g):
    # backward through the scan-flash path: same O(seq) memory class as the
    # forward, so 'auto' never changes a training run's memory behavior
    q, k, v = res
    _, vjp = jax.vjp(
        lambda q, k, v: flash_attention(q, k, v, causal=causal, scale=scale, impl="scan"),
        q,
        k,
        v,
    )
    return vjp(g)


_flash_pallas_diff.defvjp(_flash_pallas_fwd, _flash_pallas_bwd)


def flash_attention(
    q: jax.Array,
    k: jax.Array,
    v: jax.Array,
    *,
    causal: bool = False,
    scale: Optional[float] = None,
    block_size: int = 512,
    impl: str = "auto",
) -> jax.Array:
    """Blockwise online-softmax attention (flash-style).

    Memory is O(q_len·heads·head_dim) instead of O(q_len·k_len·heads).

    ``impl`` selects the backend:

    * ``'scan'`` — a ``lax.scan`` over key tiles; runs everywhere, fully
      differentiable, XLA schedules the tiles.
    * ``'pallas'`` — the hand-tiled TPU kernel (:mod:`heat_tpu.ops.flash`);
      owns the (q, k) tile grid, skips above-diagonal tiles when causal.
      Its win over dense is memory class (O(seq) vs O(seq²)); on speed the
      r04 real-v5e capture measured it at 0.44 TFLOP/s marginal vs dense's
      0.69 at 4k causal f32 with its then-default (128, 128) tiles — a
      0.65x REGRESSION (git-banked attention stage, r04 window; recovered
      per VERDICT r04). Differentiable via a custom VJP whose backward
      re-runs the scan path (same O(seq) memory).
      ``block_size`` does not apply — the kernel picks its own 128-aligned
      tiles (pass ``block_q``/``block_k`` to
      :func:`heat_tpu.ops.flash.flash_attention_tpu` directly to tune them).
    * ``'auto'`` — ``'scan'``, everywhere. The pallas kernel is opt-in until
      a banked real-TPU capture shows it beating the scan path at the
      r05 defaults (the measured-fastest path owns the default; see
      benchmarks/tpu_window.py stage_attention / stage_attention_sweep).
    """
    if impl not in ("auto", "scan", "pallas"):
        raise ValueError(f"unknown flash impl {impl!r}")
    if impl == "pallas":
        return _flash_pallas_diff(q, k, v, causal, scale)
    acc = _acc_dtype(q.dtype)
    if scale is None:
        scale = 1.0 / math.sqrt(q.shape[-1])
    B, sq, H, D = q.shape
    sk = k.shape[1]
    bk = min(block_size, sk)
    nb = -(-sk // bk)
    pad = nb * bk - sk
    if pad:
        # padded keys are masked out via the causal/index mask below
        k = jnp.pad(k, ((0, 0), (0, pad), (0, 0), (0, 0)))
        v = jnp.pad(v, ((0, 0), (0, pad), (0, 0), (0, 0)))
    ks = k.reshape(B, nb, bk, H, D).transpose(1, 0, 2, 3, 4)
    vs = v.reshape(B, nb, bk, H, D).transpose(1, 0, 2, 3, 4)

    # seed the carry from q so it has q's varying-axes type under shard_map
    # (a replicated zero carry would mismatch the varying scan outputs)
    zero = (q[(0,) * q.ndim] * 0).astype(acc)
    m0 = jnp.full((B, sq, H), -jnp.inf, acc) + zero
    l0 = jnp.zeros((B, sq, H), acc) + zero
    o0 = jnp.zeros((B, sq, H, D), acc) + zero

    def step(carry, blk):
        i, m, l, o = carry
        k_blk, v_blk = blk
        k_idx0 = i * bk
        kv_valid = k_idx0 + jnp.arange(bk) < sk
        m, l, o = _tile_update(q, k_blk, v_blk, m, l, o, 0, k_idx0, causal, scale, kv_valid)
        return (i + 1, m, l, o), None

    (_, _, l, o), _ = jax.lax.scan(step, (jnp.zeros((), jnp.int32), m0, l0, o0), (ks, vs))
    return _finalize(l, o, q.dtype)


def ring_attention(
    q: jax.Array,
    k: jax.Array,
    v: jax.Array,
    *,
    causal: bool = False,
    scale: Optional[float] = None,
    comm: Optional[MeshCommunication] = None,
) -> jax.Array:
    """Ring-parallel attention over the mesh's sequence axis.

    Q/K/V enter sharded [B, S, H, D] with S block-distributed over the mesh
    (``split=1`` in framework terms). Each device keeps its Q shard resident
    while K/V shards rotate around the ring via ``lax.ppermute`` — the exact
    communication schedule of the reference's systolic cdist
    (spatial/distance.py:272-327) — folding one tile per step into the
    online-softmax state. Per-chip memory is O(S/p); the p-1 permutes ride ICI
    and overlap with the tile matmuls under XLA's latency-hiding scheduler.
    """
    comm = sanitize_comm(comm)
    if scale is None:
        scale = 1.0 / math.sqrt(q.shape[-1])
    S = q.shape[1]
    if S % comm.size:
        raise ValueError(f"ring_attention requires seq {S} divisible by mesh size {comm.size}")
    fn = _ring_attention_fn(comm.mesh, comm.axis_name, bool(causal), float(scale))
    return fn(q, k, v)


@functools.lru_cache(maxsize=None)
def _ring_attention_fn(mesh, axis, causal, scale):
    """Jitted shard_map ring kernel, cached per (mesh, causal, scale) so eager
    callers reuse XLA's compile cache instead of retracing a fresh closure."""
    p_sz = mesh.shape[axis]

    def kernel(ql, kl, vl):
        acc = _acc_dtype(ql.dtype)
        rank = jax.lax.axis_index(axis)
        B, sq, H, D = ql.shape
        q_idx0 = rank * sq
        m0 = jnp.full((B, sq, H), -jnp.inf, acc)
        l0 = jnp.zeros((B, sq, H), acc)
        o0 = jnp.zeros((B, sq, H, D), acc)
        try:  # constants start replicated; mark them varying for the carry
            m0, l0, o0 = (jax.lax.pcast(x, (axis,), to="varying") for x in (m0, l0, o0))
        except (AttributeError, TypeError):  # pragma: no cover - older jax
            pass

        def fold(i, kc, vc, m, l, o):
            # kc/vc currently hold the shard owned by device (rank + i) % p
            k_idx0 = ((rank + i.astype(rank.dtype)) % p_sz) * sq
            return _tile_update(ql, kc, vc, m, l, o, q_idx0, k_idx0, causal, scale)

        def body(i, carry):
            kc, vc, m, l, o = carry
            m, l, o = fold(i, kc, vc, m, l, o)
            perm = [(j, (j - 1) % p_sz) for j in range(p_sz)]
            kc = jax.lax.ppermute(kc, axis, perm)
            vc = jax.lax.ppermute(vc, axis, perm)
            return kc, vc, m, l, o

        # p-1 rotations: the loop body permutes after each fold; the last
        # shard is folded outside so its rotation is never issued.
        kl, vl, m, l, o = jax.lax.fori_loop(0, p_sz - 1, body, (kl, vl, m0, l0, o0))
        m, l, o = fold(jnp.asarray(p_sz - 1), kl, vl, m, l, o)
        return _finalize(l, o, ql.dtype)

    return jax.jit(
        jax.shard_map(
            kernel,
            mesh=mesh,
            in_specs=(P(None, axis), P(None, axis), P(None, axis)),
            out_specs=P(None, axis),
        )
    )


def ulysses_attention(
    q: jax.Array,
    k: jax.Array,
    v: jax.Array,
    *,
    causal: bool = False,
    scale: Optional[float] = None,
    comm: Optional[MeshCommunication] = None,
    block_size: int = 0,
) -> jax.Array:
    """All-to-all (Ulysses) sequence-parallel attention.

    Two ``lax.all_to_all`` layout switches re-shard [B, S/p, H, D] →
    [B, S, H/p, D] (sequence-sharded → head-sharded), run full-sequence
    attention on each device's head group, and switch back — the attention
    instance of the reference's axis-changing resplit (Alltoallw,
    communication.py:336-437). Requires ``heads % p == 0``. With
    ``block_size > 0`` the local attention is the blockwise
    :func:`flash_attention` (O(S) memory); otherwise dense.
    """
    comm = sanitize_comm(comm)
    p_sz = comm.size
    H = q.shape[2]
    if H % p_sz:
        raise ValueError(f"ulysses_attention requires heads {H} divisible by mesh size {p_sz}")
    if q.shape[1] % p_sz:
        raise ValueError(f"seq {q.shape[1]} not divisible by mesh size {p_sz}")
    scale_f = float(scale) if scale is not None else 1.0 / math.sqrt(q.shape[-1])
    fn = _ulysses_attention_fn(comm.mesh, comm.axis_name, bool(causal), scale_f, int(block_size))
    return fn(q, k, v)


@functools.lru_cache(maxsize=None)
def _ulysses_attention_fn(mesh, axis, causal, scale, block_size):
    """Jitted shard_map Ulysses kernel, cached per configuration (see
    :func:`_ring_attention_fn` for why)."""
    local = (
        functools.partial(flash_attention, block_size=block_size)
        if block_size
        else dot_product_attention
    )

    def kernel(ql, kl, vl):
        # [B, S/p, H, D] -> [B, S, H/p, D]: split heads, gather sequence
        qh, kh, vh = (
            jax.lax.all_to_all(x, axis, split_axis=2, concat_axis=1, tiled=True)
            for x in (ql, kl, vl)
        )
        oh = local(qh, kh, vh, causal=causal, scale=scale)
        return jax.lax.all_to_all(oh, axis, split_axis=1, concat_axis=2, tiled=True)

    return jax.jit(
        jax.shard_map(
            kernel,
            mesh=mesh,
            in_specs=(P(None, axis), P(None, axis), P(None, axis)),
            out_specs=P(None, axis),
        )
    )


_BACKENDS: dict = {}


def _resolve_backend(name: str) -> Callable:
    if not _BACKENDS:
        _BACKENDS.update(
            dense=dot_product_attention,
            flash=flash_attention,
            ring=ring_attention,
            ulysses=ulysses_attention,
        )
    try:
        return _BACKENDS[name]
    except KeyError:
        raise ValueError(f"unknown attention backend {name!r}; one of {sorted(_BACKENDS)}")


import flax.linen as fnn


class MultiHeadAttention(fnn.Module):
    """Multi-head self-attention with a pluggable sequence-parallel backend.

    ``backend`` selects among 'dense', 'flash', 'ring', 'ulysses'. The
    projections are ordinary Dense layers (sharded by GSPMD when the
    activations are); only the score/value contraction is parallel-aware.

    This intentionally shadows ``flax.linen.MultiHeadAttention`` in the
    ``heat_tpu.nn`` namespace (different signature: no bias/dropout/decode;
    the parallel backends take ``comm``).
    """

    num_heads: int
    qkv_features: Optional[int] = None
    causal: bool = False
    backend: str = "dense"
    dtype: Optional[jnp.dtype] = None
    # direct kernel injection, overriding ``backend``: a callable
    # (q, k, v, causal=...) -> out, e.g. functools.partial(ring_attention,
    # comm=comm). One hook owns the backend plumbing for every consumer
    # (TransformerBlock composes this module rather than re-implementing it).
    attention_fn: Optional[Callable] = None

    @fnn.compact
    def __call__(self, x, comm: Optional[MeshCommunication] = None):
        features = self.qkv_features or x.shape[-1]
        if features % self.num_heads:
            raise ValueError("qkv_features must be divisible by num_heads")
        head_dim = features // self.num_heads
        dense = functools.partial(fnn.DenseGeneral, dtype=self.dtype)
        qkv_shape = (self.num_heads, head_dim)
        q = dense(features=qkv_shape, name="query")(x)
        k = dense(features=qkv_shape, name="key")(x)
        v = dense(features=qkv_shape, name="value")(x)
        kwargs = {"causal": self.causal}
        if self.attention_fn is not None:
            attn = self.attention_fn  # comm, scale etc. bound by the caller
        else:
            attn = _resolve_backend(self.backend)
            if self.backend in ("ring", "ulysses"):
                kwargs["comm"] = comm
        o = attn(q, k, v, **kwargs)
        return fnn.DenseGeneral(
            features=x.shape[-1], axis=(-2, -1), dtype=self.dtype, name="out"
        )(o)

"""Functional NN interface (reference: heat/nn/functional.py).

The reference module is a single dynamic shim forwarding attribute lookups to
``torch.nn.functional`` (reference nn/functional.py:1-20, ``func_getattr``).
The TPU-native backing functional library is ``jax.nn`` (activations,
normalization, one-hot, attention helpers), with ``flax.linen`` as a fallback
for layer-style callables, so ``heat_tpu.nn.functional.relu``,
``...softmax``, ``...one_hot`` etc. all resolve.
"""

from __future__ import annotations

import jax.nn as _jnn
import flax.linen as _linen

__all__ = ["func_getattr"]


def func_getattr(name: str):
    """Forward ``name`` to the backing functional library
    (reference nn/functional.py — ``func_getattr`` forwards to
    ``torch.nn.functional``)."""
    try:
        return getattr(_jnn, name)
    except AttributeError:
        try:
            return getattr(_linen, name)
        except AttributeError:
            raise AttributeError(f"module 'heat_tpu.nn.functional' has no attribute {name!r}")


def __getattr__(name: str):
    return func_getattr(name)

"""Data-parallel model training.

TPU-native re-design of reference heat/nn/data_parallel.py. The reference
wraps a torch module and averages gradients with per-parameter MPI hooks —
blocking Allreduce after backward (data_parallel.py:223-241) or per-layer
Iallreduce overlapped into the next forward (:243-297). Under JAX the same
semantics are one jitted, functional train step over a ``data`` mesh axis:
the batch is row-sharded, ``jax.grad`` runs on each device's shard, and GSPMD
inserts the gradient psum — overlap scheduling is the XLA latency-hiding
scheduler's job, which is precisely what the reference's non-blocking hook
machinery hand-builds.

API deviation (documented): torch's imperative ``loss.backward();
optimizer.step()`` has no JAX analog, so ``DataParallel`` owns the train
step: ``dp.train_step(batch, labels)`` runs forward+backward+update and
returns the loss. ``dp(x)`` evaluates the forward pass.
"""

from __future__ import annotations

from typing import Any, Callable, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np
import optax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from ..core.communication import MeshCommunication, sanitize_comm
from ..core.dndarray import DNDarray

__all__ = ["DataParallel", "DataParallelMultiGPU"]


def _cross_entropy_loss(logits: jax.Array, labels: jax.Array) -> jax.Array:
    if labels.ndim == logits.ndim:
        return optax.softmax_cross_entropy(logits, labels).mean()
    return optax.softmax_cross_entropy_with_integer_labels(logits, labels).mean()


class DataParallel:
    """Replica training over the mesh's data axis (reference
    data_parallel.py:21-139 constructor contract).

    Parameters
    ----------
    module : flax.linen.Module
        The network definition.
    comm : MeshCommunication, optional
        Mesh whose axis is the data-parallel axis.
    optimizer : optax.GradientTransformation, optional
        Defaults to SGD(0.01).
    loss_fn : callable(logits, labels) -> scalar, optional
        Defaults to softmax cross entropy.
    blocking_parameter_updates : bool
        Parity flag. Both reference modes (blocking hook :223-241,
        non-blocking :243-297) compile to the same fused step here; the flag
        is recorded but changes nothing.
    """

    def __init__(
        self,
        module,
        comm: Optional[MeshCommunication] = None,
        optimizer=None,
        loss_fn: Optional[Callable] = None,
        blocking_parameter_updates: bool = False,
    ):
        self.module = module
        self.comm = sanitize_comm(comm)
        self.optimizer = optimizer if optimizer is not None else optax.sgd(0.01)
        self.loss_fn = loss_fn if loss_fn is not None else _cross_entropy_loss
        self.blocking_parameter_updates = blocking_parameter_updates
        self.params = None
        self.state = None
        self.opt_state = None
        self._stateful = False
        self._train_step = None
        self._apply = None

    # ------------------------------------------------------------------
    def init(self, rng_seed: int, sample_input) -> "DataParallel":
        """Initialize parameters; replica seeds are unified as in the
        reference (data_parallel.py:107-109 seeds all ranks identically —
        with one controller there is a single init by construction)."""
        sample = self._as_jax(sample_input)
        key = jax.random.PRNGKey(rng_seed)
        variables = self.module.init(key, sample)
        # stateful modules (BatchNorm) split into trainable params + state
        self._stateful = "batch_stats" in variables
        if self._stateful:
            self.params = variables["params"]
            self.state = {k: v for k, v in variables.items() if k != "params"}
        else:
            self.params = variables
            self.state = None
        self.opt_state = self.optimizer.init(self.params)
        self._build(sample)
        return self

    def _as_jax(self, x):
        if isinstance(x, DNDarray):
            return x.larray
        return jnp.asarray(x)

    def _replicated(self) -> NamedSharding:
        return NamedSharding(self.comm.mesh, P())

    def _build(self, sample):
        rep = self._replicated()

        if self._stateful:

            def step(params, state, opt_state, x, y):
                def loss_of(p):
                    logits, new_model_state = self.module.apply(
                        {"params": p, **state}, x, train=True, mutable=["batch_stats"]
                    )
                    return self.loss_fn(logits, y), new_model_state

                (loss, new_state), grads = jax.value_and_grad(loss_of, has_aux=True)(params)
                updates, opt_state = self.optimizer.update(grads, opt_state, params)
                params = optax.apply_updates(params, updates)
                return params, new_state, opt_state, loss

            self._train_step = jax.jit(step, out_shardings=(rep, rep, rep, rep))
            self._apply = jax.jit(
                lambda params, state, x: self.module.apply({"params": params, **state}, x)
            )
        else:

            def step(params, opt_state, x, y):
                def loss_of(p):
                    logits = self.module.apply(p, x)
                    return self.loss_fn(logits, y)

                loss, grads = jax.value_and_grad(loss_of)(params)
                updates, opt_state = self.optimizer.update(grads, opt_state, params)
                params = optax.apply_updates(params, updates)
                return params, opt_state, loss

            # batch sharded over the data axis; params/opt state replicated —
            # GSPMD inserts the grad psum the reference does with MPI hooks
            self._train_step = jax.jit(step, out_shardings=(rep, rep, rep))
            self._apply = jax.jit(self.module.apply)

    # ------------------------------------------------------------------
    def __call__(self, x):
        """Forward pass (reference data_parallel.py:140-174)."""
        if self.params is None:
            raise RuntimeError("DataParallel.init must be called before the forward pass")
        if self._stateful:
            return self._apply(self.params, self.state, self._as_jax(x))
        return self._apply(self.params, self._as_jax(x))

    forward = __call__

    def train_step(self, x, y) -> float:
        """One optimization step on a (sharded) batch; returns the loss."""
        if self.params is None:
            raise RuntimeError("DataParallel.init must be called before training")
        from ..core.dndarray import _ensure_split

        xj, yj = self._as_jax(x), self._as_jax(y)
        # _ensure_split tolerates batch sizes not divisible by the mesh
        # (jitted with_sharding_constraint fallback)
        xb = _ensure_split(xj, 0, self.comm)
        yb = _ensure_split(yj, 0, self.comm)
        from ..core import numlens

        prev = self.params if numlens.active() else None
        if self._stateful:
            self.params, self.state, self.opt_state, loss = self._train_step(
                self.params, self.state, self.opt_state, xb, yb
            )
        else:
            self.params, self.opt_state, loss = self._train_step(
                self.params, self.opt_state, xb, yb
            )
        if prev is not None:
            # numerics lens (HEAT_TPU_NUMLENS): per-step loss / update-ratio
            # streams + plateau/overflow detection over the synced gradients
            numlens.note_training(
                "data_parallel.step", loss=loss,
                params=self.params, prev_params=prev,
            )
        return float(loss)

    # ------------------------------------------------------------------
    # checkpoint / resume (no reference analog: the reference checkpoints
    # data only, io.py:149-227 — model/optimizer resume is TPU-build new).
    # state_dict/load_state_dict have the same full-trainer-state meaning
    # here as on DASO.
    # ------------------------------------------------------------------
    def state_dict(self):
        """Full resumable state: params, model state, and optimizer state."""
        return {
            "params": self.params,
            "state": self.state if self.state is not None else {},
            "opt_state": self.opt_state,
        }

    def load_state_dict(self, sd) -> "DataParallel":
        """Restore :meth:`state_dict` output. A bare params pytree (the
        torch-parity shape) is also accepted — optimizer state then restarts."""
        if isinstance(sd, dict) and "params" in sd and "opt_state" in sd:
            self.params = sd["params"]
            if self._stateful:
                self.state = sd["state"]
            self.opt_state = sd["opt_state"]
        else:
            self.params = sd
            self.opt_state = self.optimizer.init(sd)
        return self

    def rebind(self, comm: Optional[MeshCommunication] = None) -> "DataParallel":
        """Re-target the trainer onto a (possibly shrunk) world — the
        elastic reform step. Replicated state is mesh-shape-independent, so
        rebinding is re-placement onto the new mesh's replicated sharding
        plus a rebuild of the jitted step (whose ``out_shardings`` name the
        old mesh)."""
        self.comm = sanitize_comm(comm)
        if self.params is not None:
            rep = self._replicated()
            place = lambda t: jax.tree.map(
                lambda a: jax.device_put(a, rep) if hasattr(a, "shape") else a, t
            )
            self.params = place(self.params)
            if self.state is not None:
                self.state = place(self.state)
            self.opt_state = place(self.opt_state)
        if self._train_step is not None:
            self._build(None)
        return self

    def fit(self, batches, **kwargs):
        """Preemption-tolerant training over ``batches`` — delegates to
        :func:`heat_tpu.elastic.fit` (see core/elastic.py for the knobs)."""
        from ..core import elastic

        return elastic.fit(self, batches, **kwargs)

    def save(self, directory: str, step: int = 0, keep: int = 3) -> str:
        """Write a manifest-based checkpoint ``directory/ckpt_{step}.manifest.json``
        (+ per-leaf payload files; the manifest rename is the commit point —
        a crash never leaves a torn checkpoint). Keeps the newest ``keep``."""
        from ..utils.checkpoint import save_checkpoint

        return save_checkpoint(directory, self.state_dict(), step=step, keep=keep)

    def restore(
        self, directory: str, step: Optional[int] = None, strict: bool = False
    ) -> "DataParallel":
        """Resume from a checkpoint written by :meth:`save`.

        ``step=None`` restores the newest checkpoint that *verifies*
        (checksum-checked; unverifiable newer ones are skipped with a
        warning — ``strict=True`` raises instead). An explicit ``step`` that
        does not exist on disk raises ``FileNotFoundError`` listing the
        available steps rather than silently loading the newest."""
        from ..utils.checkpoint import load_checkpoint

        return self.load_state_dict(
            load_checkpoint(directory, self.state_dict(), step=step, strict=strict)
        )


class DataParallelMultiGPU(DataParallel):
    """Node-local data parallelism bound to a DASO optimizer (reference
    data_parallel.py:314-376: wraps the model in torch-DDP over the node's
    GPUs and hands the gradient stream to DASO).

    The TPU rendering: construct with a :class:`~heat_tpu.optim.DASO`
    instance and this wrapper attaches the module to it (``daso.add_model``)
    — ``step``/``forward``/checkpointing then delegate to DASO's 2-axis
    (dcn x ici) schedule, which owns the intra-node sync cadence the
    reference's DDP wrapper provided. Without a DASO it degrades to plain
    :class:`DataParallel` over the full mesh (the reference class likewise
    requires its optimizer to be useful).
    """

    def __init__(self, module, optimizer=None, comm=None, rng_seed: int = 0,
                 sample_input=None, **kwargs):
        from ..optim.dp_optimizer import DASO

        self.daso: Optional["DASO"] = None
        if isinstance(optimizer, DASO):
            if sample_input is None:
                raise ValueError(
                    "binding DataParallelMultiGPU to a DASO requires sample_input "
                    "(the reference's DDP wrapper likewise needs a model pass "
                    "to register its gradient hooks)"
                )
            self.daso = optimizer
            self.module = module
            self.comm = optimizer.comm
            optimizer.add_model(module, rng_seed, sample_input)
            return
        super().__init__(module, comm=comm, optimizer=optimizer, **kwargs)

    def step(self, x, y):
        if self.daso is not None:
            return self.daso.step(x, y)
        return super().step(x, y)

    def forward(self, x):
        if self.daso is not None:
            return self.daso.forward(x)
        return super().forward(x)

    __call__ = forward

    def rebind(self, comm=None):
        if self.daso is not None:
            self.daso.rebind(comm)
            self.comm = self.daso.comm
            return self
        return super().rebind(comm)

    def save(self, directory: str, step: int = 0, keep: int = 3) -> str:
        if self.daso is not None:
            return self.daso.save(directory, step=step, keep=keep)
        return super().save(directory, step=step, keep=keep)

    def restore(self, directory: str, step: Optional[int] = None, strict: bool = False):
        if self.daso is not None:
            self.daso.restore(directory, step=step, strict=strict)
            return self
        return super().restore(directory, step=step, strict=strict)

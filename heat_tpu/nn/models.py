"""Reference network definitions, TPU-first.

The reference has no model zoo (its examples build torch CNNs inline,
examples/nn/mnist.py:20-48); this module provides the flagship models the
benchmarks need, designed for the MXU: NHWC layouts, channel counts in
multiples of 8/128, bfloat16-friendly, no data-dependent control flow.
"""

from __future__ import annotations

from functools import partial
from typing import Any, Callable, Sequence, Tuple

import flax.linen as fnn
import jax.numpy as jnp

__all__ = [
    "MLP",
    "SimpleCNN",
    "ResNet",
    "ResNet18",
    "ResNet50",
    "BasicBlock",
    "Bottleneck",
    "TransformerBlock",
    "TransformerLM",
]


class MLP(fnn.Module):
    """Small multilayer perceptron (the reference's mnist example net shape)."""

    features: Sequence[int] = (128, 10)
    dtype: Any = jnp.float32

    @fnn.compact
    def __call__(self, x):
        x = x.reshape((x.shape[0], -1)).astype(self.dtype)
        for feat in self.features[:-1]:
            x = fnn.relu(fnn.Dense(feat, dtype=self.dtype)(x))
        return fnn.Dense(self.features[-1], dtype=self.dtype)(x)


class SimpleCNN(fnn.Module):
    """Conv net matching the reference example (examples/nn/mnist.py:20-48)."""

    num_classes: int = 10
    dtype: Any = jnp.float32

    @fnn.compact
    def __call__(self, x):
        if x.ndim == 3:
            x = x[..., None]
        x = x.astype(self.dtype)
        x = fnn.relu(fnn.Conv(32, (3, 3), dtype=self.dtype)(x))
        x = fnn.relu(fnn.Conv(64, (3, 3), dtype=self.dtype)(x))
        x = fnn.max_pool(x, (2, 2), strides=(2, 2))
        x = x.reshape((x.shape[0], -1))
        x = fnn.relu(fnn.Dense(128, dtype=self.dtype)(x))
        return fnn.Dense(self.num_classes, dtype=self.dtype)(x)


class BasicBlock(fnn.Module):
    """3x3+3x3 residual block (ResNet-18/34)."""

    filters: int
    strides: Tuple[int, int] = (1, 1)
    dtype: Any = jnp.float32

    @fnn.compact
    def __call__(self, x, train: bool = False):
        norm = partial(fnn.BatchNorm, use_running_average=not train, dtype=self.dtype)
        residual = x
        y = fnn.Conv(self.filters, (3, 3), self.strides, padding=1, use_bias=False, dtype=self.dtype)(x)
        y = fnn.relu(norm()(y))
        y = fnn.Conv(self.filters, (3, 3), padding=1, use_bias=False, dtype=self.dtype)(y)
        y = norm(scale_init=fnn.initializers.zeros_init())(y)
        if residual.shape != y.shape:
            residual = fnn.Conv(
                self.filters, (1, 1), self.strides, use_bias=False, dtype=self.dtype
            )(residual)
            residual = norm()(residual)
        return fnn.relu(y + residual)


class Bottleneck(fnn.Module):
    """1x1-3x3-1x1 bottleneck block (ResNet-50/101/152)."""

    filters: int
    strides: Tuple[int, int] = (1, 1)
    dtype: Any = jnp.float32

    @fnn.compact
    def __call__(self, x, train: bool = False):
        norm = partial(fnn.BatchNorm, use_running_average=not train, dtype=self.dtype)
        residual = x
        y = fnn.Conv(self.filters, (1, 1), use_bias=False, dtype=self.dtype)(x)
        y = fnn.relu(norm()(y))
        y = fnn.Conv(self.filters, (3, 3), self.strides, padding=1, use_bias=False, dtype=self.dtype)(y)
        y = fnn.relu(norm()(y))
        y = fnn.Conv(self.filters * 4, (1, 1), use_bias=False, dtype=self.dtype)(y)
        y = norm(scale_init=fnn.initializers.zeros_init())(y)
        if residual.shape != y.shape:
            residual = fnn.Conv(
                self.filters * 4, (1, 1), self.strides, use_bias=False, dtype=self.dtype
            )(residual)
            residual = norm()(residual)
        return fnn.relu(y + residual)


class ResNet(fnn.Module):
    """CIFAR-style ResNet (3x3 stem, no max-pool) in NHWC.

    stage_sizes/block pick the variant; dtype=jnp.bfloat16 runs the matmuls
    and convs on the MXU at full rate with float32 batch-norm statistics.
    """

    stage_sizes: Sequence[int]
    block: Any = BasicBlock
    num_classes: int = 10
    num_filters: int = 64
    dtype: Any = jnp.float32

    @fnn.compact
    def __call__(self, x, train: bool = False):
        norm = partial(fnn.BatchNorm, use_running_average=not train, dtype=self.dtype)
        x = x.astype(self.dtype)
        x = fnn.Conv(self.num_filters, (3, 3), padding=1, use_bias=False, dtype=self.dtype)(x)
        x = fnn.relu(norm()(x))
        for i, size in enumerate(self.stage_sizes):
            for j in range(size):
                strides = (2, 2) if i > 0 and j == 0 else (1, 1)
                x = self.block(
                    self.num_filters * 2**i, strides=strides, dtype=self.dtype
                )(x, train=train)
        x = jnp.mean(x, axis=(1, 2))
        x = fnn.Dense(self.num_classes, dtype=jnp.float32)(x)
        return x


def ResNet18(num_classes: int = 10, dtype=jnp.float32) -> ResNet:
    return ResNet(stage_sizes=(2, 2, 2, 2), block=BasicBlock, num_classes=num_classes, dtype=dtype)


def ResNet50(num_classes: int = 10, dtype=jnp.float32) -> ResNet:
    return ResNet(stage_sizes=(3, 4, 6, 3), block=Bottleneck, num_classes=num_classes, dtype=dtype)


class TransformerBlock(fnn.Module):
    """Pre-norm transformer block (attention + MLP, residual both).

    The attention callable is INJECTED so the same module runs dense
    single-chip (the default, ``nn.attention.dot_product_attention``) or
    sequence-parallel over a mesh (pass ``nn.attention.ring_attention`` /
    ``ulysses_attention`` partials) — long-context execution is a deployment
    choice, not a different model. Head dims stay in MXU-friendly multiples;
    no data-dependent control flow.
    """

    dim: int
    heads: int = 4
    mlp_ratio: int = 4
    causal: bool = True
    dtype: Any = jnp.float32
    attention_fn: Any = None  # (q, k, v, causal=...) -> out; default dense

    @fnn.compact
    def __call__(self, x):  # x: [batch, seq, dim]
        from .attention import MultiHeadAttention

        h = fnn.LayerNorm(dtype=self.dtype)(x)
        # qkv/backed-attention/out plumbing lives in ONE module —
        # MultiHeadAttention — with the kernel injected through its hook
        out = MultiHeadAttention(
            num_heads=self.heads,
            qkv_features=self.dim,
            causal=self.causal,
            dtype=self.dtype,
            attention_fn=self.attention_fn,
        )(h)
        x = x + out
        h = fnn.LayerNorm(dtype=self.dtype)(x)
        h = fnn.Dense(self.mlp_ratio * self.dim, dtype=self.dtype)(h)
        h = fnn.gelu(h)
        x = x + fnn.Dense(self.dim, dtype=self.dtype)(h)
        return x


class TransformerLM(fnn.Module):
    """Decoder-only language model (embeddings + N blocks + tied-untied head).

    The flagship long-context model family: with ``attention_fn`` left at
    the dense default it is the single-chip forward the driver
    compile-checks; with ring/Ulysses attention injected per block the
    attention contraction runs sequence-parallel over the mesh — O(S/p)
    per-chip ATTENTION memory (no S x S score matrix is ever materialized;
    the surrounding Dense/LayerNorm activations stay [B, S, dim] unless the
    caller shards them with pjit/sharding constraints).
    """

    vocab: int = 256
    dim: int = 128
    depth: int = 2
    heads: int = 4
    max_len: int = 2048
    causal: bool = True
    dtype: Any = jnp.float32
    attention_fn: Any = None

    @fnn.compact
    def __call__(self, tokens):  # tokens: [batch, seq] int
        if tokens.shape[1] > self.max_len:
            # jnp gather CLAMPS out-of-bounds indices — over-length input
            # would silently reuse the last positional row instead of failing
            raise ValueError(
                f"sequence length {tokens.shape[1]} exceeds max_len {self.max_len}"
            )
        x = fnn.Embed(self.vocab, self.dim, dtype=self.dtype)(tokens)
        pos = fnn.Embed(self.max_len, self.dim, dtype=self.dtype)(
            jnp.arange(tokens.shape[1])[None, :]
        )
        x = x + pos
        for _ in range(self.depth):
            x = TransformerBlock(
                dim=self.dim,
                heads=self.heads,
                causal=self.causal,
                dtype=self.dtype,
                attention_fn=self.attention_fn,
            )(x)
        x = fnn.LayerNorm(dtype=self.dtype)(x)
        return fnn.Dense(self.vocab, dtype=jnp.float32)(x)

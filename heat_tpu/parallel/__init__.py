"""Parallelism-strategy package.

The reference's parallelism vocabulary is 1-D data/array parallelism plus
resharding, rings, halos, and hierarchical DP (SURVEY.md §2.3); TP/PP/EP are
explicitly absent there. This package makes them first-class for the TPU
build, on top of multi-axis ``jax.sharding.Mesh``es:

- :func:`make_mesh` — named multi-axis meshes ('dp', 'tp', 'pp', 'ep', ...).
- :mod:`tensor <heat_tpu.parallel.tensor>` — Megatron-style column/row
  parallel Dense layers expressed as GSPMD sharding constraints (XLA inserts
  the all-gather/reduce-scatter; nothing is hand-scheduled).
- :mod:`pipeline <heat_tpu.parallel.pipeline>` — GPipe-style microbatched
  pipeline over a mesh axis via ``shard_map`` + ``ppermute`` (the schedule IS
  the algorithm, so it is written explicitly).
- :mod:`expert <heat_tpu.parallel.expert>` — top-1 mixture-of-experts layer
  with ``all_to_all`` token dispatch over the expert axis.

Sequence parallelism (ring / Ulysses attention) lives in
:mod:`heat_tpu.nn.attention` and composes with these meshes.
"""

from __future__ import annotations

from typing import Optional, Sequence, Tuple

import jax
import numpy as np
from jax.sharding import Mesh

from .expert import MoELayer, moe_apply
from .pipeline import pipeline_apply, pipeline_stage_params
from .tensor import ColumnParallelDense, RowParallelDense, TPMLPBlock

__all__ = [
    "ColumnParallelDense",
    "MoELayer",
    "RowParallelDense",
    "TPMLPBlock",
    "make_mesh",
    "moe_apply",
    "pipeline_apply",
    "pipeline_stage_params",
]


def make_mesh(
    axes: Sequence[Tuple[str, int]],
    devices: Optional[Sequence] = None,
) -> Mesh:
    """Build a named multi-axis mesh, e.g. ``make_mesh([("dp", 2), ("tp", 4)])``.

    Axis sizes must multiply to the device count. Axis order fixes locality:
    later axes are nearest neighbors (put 'tp' last so its collectives ride
    the fastest interconnect, the standard TPU layout recipe).
    """
    if devices is None:
        devices = jax.devices()
    names = tuple(n for n, _ in axes)
    sizes = tuple(int(s) for _, s in axes)
    total = int(np.prod(sizes))
    if total != len(devices):
        raise ValueError(
            f"mesh axes {dict(axes)} need {total} devices, have {len(devices)}"
        )
    return Mesh(np.asarray(devices).reshape(sizes), names)

"""Tensor (model) parallelism: Megatron-style sharded Dense layers.

The reference has no TP (SURVEY.md §2.3 marks it absent); this is the
TPU-native extension. Nothing here hand-schedules communication: the kernels
carry ``PartitionSpec`` annotations (flax ``with_partitioning`` metadata) and
the activations receive ``with_sharding_constraint``s; GSPMD inserts the
all-gather / reduce-scatter pair that realizes the Megatron column→row
pattern, overlapped by XLA's latency-hiding scheduler.

Axis conventions: 'tp' = tensor axis, 'dp' = data axis (batch). Use
:func:`heat_tpu.parallel.make_mesh` to build the mesh and run the module
under ``jax.jit`` inside ``with mesh:`` (or pass shardings explicitly).
"""

from __future__ import annotations

from typing import Callable, Optional

import flax.linen as nn
import jax
import jax.numpy as jnp
from jax.sharding import PartitionSpec as P

__all__ = ["ColumnParallelDense", "RowParallelDense", "TPMLPBlock"]


def _constrain_last(x, axis_name):
    """Constrain only the feature (last) dim; leading dims (batch/seq) keep
    whatever sharding the data came with (UNCONSTRAINED), so a dp-sharded
    batch is not gathered. No-op outside a mesh context."""
    spec = P(*([P.UNCONSTRAINED] * (x.ndim - 1)), axis_name)
    # the no-op fallback IS this helper's contract ("No-op outside a mesh
    # context", docstring above): which exception an unresolved axis name
    # raises varies by jax version/trace context, and the unconstrained
    # layer remains numerically correct either way
    try:
        return jax.lax.with_sharding_constraint(x, spec)
    # heat-lint: disable=H003 — no-op outside a mesh context is the contract
    except Exception:
        return x


class ColumnParallelDense(nn.Module):
    """Dense whose kernel is column-sharded over 'tp': y[..., f] with f
    partitioned. The activation stays tp-sharded — feed it to a
    :class:`RowParallelDense` to contract it back (the Megatron pair)."""

    features: int
    use_bias: bool = True
    dtype: Optional[jnp.dtype] = None
    kernel_init: Callable = nn.initializers.lecun_normal()
    tp_axis: str = "tp"

    @nn.compact
    def __call__(self, x):
        kernel = self.param(
            "kernel",
            nn.with_partitioning(self.kernel_init, (None, self.tp_axis)),
            (x.shape[-1], self.features),
            self.dtype or x.dtype,
        )
        y = x @ kernel
        if self.use_bias:
            bias = self.param(
                "bias",
                nn.with_partitioning(nn.initializers.zeros_init(), (self.tp_axis,)),
                (self.features,),
                self.dtype or x.dtype,
            )
            y = y + bias
        return _constrain_last(y, self.tp_axis)


class RowParallelDense(nn.Module):
    """Dense whose kernel is row-sharded over 'tp': contracts a tp-sharded
    input; GSPMD inserts the psum (all-reduce) over 'tp' for the partial
    products. Output is replicated across 'tp'."""

    features: int
    use_bias: bool = True
    dtype: Optional[jnp.dtype] = None
    kernel_init: Callable = nn.initializers.lecun_normal()
    tp_axis: str = "tp"

    @nn.compact
    def __call__(self, x):
        kernel = self.param(
            "kernel",
            nn.with_partitioning(self.kernel_init, (self.tp_axis, None)),
            (x.shape[-1], self.features),
            self.dtype or x.dtype,
        )
        y = x @ kernel
        if self.use_bias:
            # bias is added once, after the implicit psum — replicated
            bias = self.param(
                "bias", nn.initializers.zeros_init(), (self.features,), self.dtype or x.dtype
            )
            y = y + bias
        return _constrain_last(y, None)


class TPMLPBlock(nn.Module):
    """The canonical 2-layer TP block: column-parallel up-projection, gelu,
    row-parallel down-projection. One all-reduce per block, like Megatron."""

    hidden: int
    features: int
    tp_axis: str = "tp"

    @nn.compact
    def __call__(self, x):
        h = ColumnParallelDense(self.hidden, tp_axis=self.tp_axis, name="up")(x)
        h = nn.gelu(h)
        return RowParallelDense(self.features, tp_axis=self.tp_axis, name="down")(h)

"""Expert parallelism: top-1 mixture-of-experts with all_to_all dispatch.

Absent from the reference (SURVEY.md §2.3); TPU-native here. One expert per
device along the 'ep' mesh axis. Tokens are routed top-1, packed into fixed
per-destination buffers (capacity = local token count, so nothing is ever
dropped), exchanged with ``lax.all_to_all`` over ICI, transformed by the
local expert FFN, and exchanged back — the Switch-Transformer data path.
"""

from __future__ import annotations

from typing import Any, Callable

import flax.linen as nn
import jax
import jax.numpy as jnp
from jax.sharding import Mesh, PartitionSpec as P

from ..core import communication as comm_lib

__all__ = ["MoELayer", "moe_apply"]


def moe_apply(
    expert_fn: Callable[[Any, jax.Array], jax.Array],
    expert_params: Any,
    router_weights: jax.Array,
    x: jax.Array,
    mesh: Mesh,
    axis: str = "ep",
):
    """Route row-sharded tokens ``x (n, d)`` through per-device experts.

    ``expert_params`` has a leading expert axis of size E (sharded over
    ``axis``); ``router_weights (d, E)`` is replicated. Returns (n, d)
    sharded like ``x``, each token scaled by its router probability
    (straight-through top-1, Switch style).
    """
    n_exp = mesh.shape[axis]
    if x.shape[0] % n_exp:
        raise ValueError(f"token count {x.shape[0]} not divisible by {n_exp} experts")

    def kernel(p, rw, xs):
        p = jax.tree.map(lambda a: a[0], p)  # this device's expert
        t = xs.shape[0]  # local tokens; also the per-destination capacity
        logits = xs @ rw  # (t, E)
        probs = jax.nn.softmax(logits, axis=-1)
        assign = jnp.argmax(logits, axis=-1)  # (t,)
        gate = jnp.take_along_axis(probs, assign[:, None], axis=1)[:, 0]  # (t,)

        # pack: slot j*t + rank-within-expert-j (capacity t never overflows)
        onehot = jax.nn.one_hot(assign, n_exp, dtype=jnp.int32)  # (t, E)
        rank = (jnp.cumsum(onehot, axis=0) - 1)  # rank among same-expert tokens
        slot = assign * t + jnp.take_along_axis(rank, assign[:, None], axis=1)[:, 0]
        dispatch = jnp.zeros((n_exp * t, xs.shape[1]), xs.dtype).at[slot].set(xs)
        dispatch = dispatch.reshape(n_exp, t, xs.shape[1])

        # exchange: block j goes to device j; we receive one block per source
        received = comm_lib.alltoall(dispatch, axis, split_axis=0, concat_axis=0)
        flat = received.reshape(n_exp * t, xs.shape[1])
        transformed = expert_fn(p, flat).reshape(n_exp, t, xs.shape[1])

        # return trip and unpack to original token order
        back = comm_lib.alltoall(transformed, axis, split_axis=0, concat_axis=0)
        out = back.reshape(n_exp * t, xs.shape[1])[slot]
        return out * gate[:, None]

    return jax.jit(
        jax.shard_map(
            kernel,
            mesh=mesh,
            in_specs=(P(axis), P(), P(axis)),
            out_specs=P(axis),
            check_vma=False,
        )
    )(expert_params, router_weights, x)


class MoELayer(nn.Module):
    """Flax wrapper: a bank of E expert MLPs + router, applied via
    :func:`moe_apply` when given a mesh, or densely (oracle path) without."""

    n_experts: int
    hidden: int
    features: int

    def setup(self):
        self.router = self.param(
            "router", nn.initializers.lecun_normal(), (self.features, self.n_experts)
        )
        self.wi = self.param(
            "wi", nn.initializers.lecun_normal(), (self.n_experts, self.features, self.hidden)
        )
        self.wo = self.param(
            "wo", nn.initializers.lecun_normal(), (self.n_experts, self.hidden, self.features)
        )

    @staticmethod
    def expert_fn(p, x):
        wi, wo = p
        return jax.nn.gelu(x @ wi) @ wo

    def __call__(self, x, mesh: Mesh = None, axis: str = "ep"):
        if mesh is not None:
            return moe_apply(
                self.expert_fn, (self.wi, self.wo), self.router, x, mesh, axis
            )
        # dense oracle: every token through its argmax expert, locally
        logits = x @ self.router
        probs = jax.nn.softmax(logits, axis=-1)
        assign = jnp.argmax(logits, axis=-1)
        gate = jnp.take_along_axis(probs, assign[:, None], axis=1)[:, 0]
        per_expert = jnp.einsum("td,edh->teh", x, self.wi)
        per_expert = jax.nn.gelu(per_expert)
        outs = jnp.einsum("teh,ehd->ted", per_expert, self.wo)
        picked = jnp.take_along_axis(outs, assign[:, None, None], axis=1)[:, 0]
        return picked * gate[:, None]

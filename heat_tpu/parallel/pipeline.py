"""Pipeline parallelism: GPipe-style microbatch schedule over a mesh axis.

Absent from the reference (SURVEY.md §2.3); built TPU-native here. Unlike the
TP layers (where GSPMD infers communication), a pipeline's schedule IS the
algorithm, so it is written explicitly with ``shard_map``: each device owns
one stage's parameters, activations hop stage→stage over ``ppermute`` (one
ICI neighbor exchange per tick), and the classic GPipe fill/drain ramp runs
``M + P - 1`` ticks for M microbatches on P stages.

Stages must be homogeneous (same activation shape in/out), the standard
transformer-block setting.
"""

from __future__ import annotations

from typing import Any, Callable, Optional, Sequence

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, PartitionSpec as P

from ..core import communication as comm_lib

__all__ = ["pipeline_apply", "pipeline_stage_params"]


def pipeline_stage_params(per_stage_params: Sequence[Any]):
    """Stack a list of per-stage param pytrees along a new leading axis
    (shard it over the 'pp' mesh axis when placing)."""
    return jax.tree.map(lambda *leaves: jnp.stack(leaves), *per_stage_params)


def pipeline_apply(
    stage_fn: Callable[[Any, jax.Array], jax.Array],
    stacked_params: Any,
    x: jax.Array,
    mesh: Mesh,
    axis: str = "pp",
    n_microbatches: Optional[int] = None,
):
    """Run ``x`` through P pipeline stages: ``stage_fn(params_p, act)`` per
    stage, microbatched over the leading (batch) axis.

    ``stacked_params`` has a leading stage axis of size P (see
    :func:`pipeline_stage_params`); it is consumed sharded over ``axis``.
    Returns the output batch, replicated (identical on every pipeline rank).
    """
    n_stages = mesh.shape[axis]
    m = n_microbatches or n_stages
    batch = x.shape[0]
    if batch % m:
        raise ValueError(f"batch {batch} not divisible by {m} microbatches")
    micro = x.reshape(m, batch // m, *x.shape[1:])

    def kernel(p, xm):
        p = jax.tree.map(lambda a: a[0], p)  # this device's stage
        stage = jax.lax.axis_index(axis)
        fwd = [(i, (i + 1) % n_stages) for i in range(n_stages)]

        def body(t, carry):
            buf, outs = carry
            # stage 0 ingests microbatch t while it exists; later stages
            # consume what the previous stage sent last tick
            idx = jnp.clip(t, 0, m - 1)
            inp = jnp.where(stage == 0, xm[idx], buf)
            out = stage_fn(p, inp)
            emit_t = t - (n_stages - 1)
            is_emit = (stage == n_stages - 1) & (emit_t >= 0)
            outs = jnp.where(
                is_emit,
                outs.at[jnp.clip(emit_t, 0, m - 1)].set(out),
                outs,
            )
            buf = comm_lib.ppermute(out, axis, n_stages, perm=fwd)
            return buf, outs

        buf0 = jnp.zeros_like(xm[0])
        outs0 = jnp.zeros(xm.shape, xm.dtype)
        _, outs = jax.lax.fori_loop(0, m + n_stages - 1, body, (buf0, outs0))
        # only the last stage holds real outputs; the sum-bcast replicates them
        outs = comm_lib.allreduce(
            jnp.where(stage == n_stages - 1, outs, jnp.zeros_like(outs)), axis, "sum"
        )
        return outs

    out = jax.jit(
        jax.shard_map(
            kernel,
            mesh=mesh,
            in_specs=(P(axis), P()),
            out_specs=P(),
            check_vma=False,
        )
    )(stacked_params, micro)
    return out.reshape(batch, *out.shape[2:])

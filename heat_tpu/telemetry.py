"""``python -m heat_tpu.telemetry`` — the observability CLI.

Pretty-prints and diffs ``ht.telemetry.report_json`` artifacts and validates
exported Chrome/Perfetto trace files without writing any analysis code:

.. code-block:: console

    $ python -m heat_tpu.telemetry show telemetry.json
    $ python -m heat_tpu.telemetry diff before.json after.json
    $ python -m heat_tpu.telemetry validate-trace trace.json
    $ python -m heat_tpu.telemetry analyze trace.json           # tracelens verdict
    $ python -m heat_tpu.telemetry analyze new.json --against old.json --json
    $ python -m heat_tpu.telemetry memory                 # live process ledger
    $ python -m heat_tpu.telemetry memory report.json --json
    $ python -m heat_tpu.telemetry health                 # flight/watchdog/SLO
    $ python -m heat_tpu.telemetry health flight_dump.json
    $ python -m heat_tpu.telemetry numerics               # stats/drift/SDC lens
    $ python -m heat_tpu.telemetry numerics report.json --json
    $ python -m heat_tpu.telemetry ops scrape --port 9464       # GET /metrics
    $ python -m heat_tpu.telemetry ops check --port 9464        # strict exposition + /healthz
    $ python -m heat_tpu.telemetry ops serve --port 9464        # serve this process

The implementation (and all state) lives in :mod:`heat_tpu.core.telemetry`;
this module is a thin proxy (``heat_tpu.telemetry.report`` etc. delegate
there live), existing so the CLI has a stable ``-m`` entry point.
"""

from __future__ import annotations

import argparse
import json
import sys
from typing import Any, Dict, List, Optional

from heat_tpu.core import telemetry as _core


def __getattr__(name):
    # live proxy: heat_tpu.telemetry.<anything> == heat_tpu.core.telemetry.<anything>
    return getattr(_core, name)


def __dir__():
    return sorted(set(globals()) | set(dir(_core)))


# ----------------------------------------------------------------------
# show
# ----------------------------------------------------------------------
def _load(path: str) -> Dict[str, Any]:
    with open(path) as fh:
        return json.load(fh)


def _fmt_bytes(n) -> str:
    try:
        n = float(n)
    except (TypeError, ValueError):
        return str(n)
    for unit in ("B", "KiB", "MiB", "GiB", "TiB"):
        if abs(n) < 1024.0 or unit == "TiB":
            return f"{n:.1f} {unit}" if unit != "B" else f"{int(n)} B"
        n /= 1024.0
    return f"{n:.1f} TiB"  # pragma: no cover - loop always returns


def _show(doc: Dict[str, Any], out) -> None:
    print(f"mode: {doc.get('mode', '?')}  enabled: {doc.get('enabled')}", file=out)
    colls = doc.get("collectives") or {}
    if colls:
        print("collectives:", file=out)
        for op, rec in sorted(colls.items(), key=lambda kv: -kv[1].get("count", 0)):
            print(
                f"  {op:<20} x{rec.get('count', 0):<8} {_fmt_bytes(rec.get('bytes', 0))}",
                file=out,
            )
    fused = doc.get("fused_collectives") or {}
    if fused:
        print("fused collective nodes:", file=out)
        for op, n in sorted(fused.items(), key=lambda kv: -kv[1]):
            print(f"  {op:<28} x{n}", file=out)
    asyncf = doc.get("async_forcing") or {}
    if asyncf:
        print(
            f"async forcing: {asyncf.get('dispatches', 0)} dispatches "
            f"({asyncf.get('roots_dispatched', 0)} roots, "
            f"{asyncf.get('multi_root_batches', 0)} batched) / "
            f"{asyncf.get('blocking_total', 0)} blocking syncs "
            f"{asyncf.get('blocking_syncs', {})}",
            file=out,
        )
    forces = doc.get("forcing_points") or {}
    if forces:
        print("forcing points:", file=out)
        for trig, rec in sorted(forces.items(), key=lambda kv: -kv[1].get("count", 0)):
            print(
                f"  {trig:<12} x{rec.get('count', 0):<7} mean depth "
                f"{rec.get('mean_depth', 0)} (max {rec.get('max_depth', 0)}, "
                f"{rec.get('compiles', 0)} compiles)",
                file=out,
            )
    progs = (doc.get("programs") or {}).get("top") or []
    if progs:
        print(f"top programs (of {doc.get('programs', {}).get('cached', 0)} cached):", file=out)
        for rec in progs:
            line = (
                f"  {rec.get('key', '?'):<18} x{rec.get('dispatches', 0):<6} "
                f"{rec.get('family', '')[:60]}"
            )
            cost = rec.get("cost") or {}
            if cost.get("flops") is not None:
                line += f"  [{cost['flops']:.0f} flops, {_fmt_bytes(cost.get('bytes_accessed'))}]"
            print(line, file=out)
    spans = doc.get("spans") or {}
    if spans:
        print("spans:", file=out)
        for path, rec in sorted(spans.items(), key=lambda kv: -kv[1].get("total_s", 0.0)):
            print(
                f"  {path:<28} x{rec.get('calls', 0):<5} {rec.get('total_s', 0.0):.4f}s",
                file=out,
            )
    scopes = doc.get("scopes") or {}
    if scopes:
        print("scopes:", file=out)
        for path, rec in sorted(scopes.items()):
            blk = rec.get("async_forcing") or {}
            print(
                f"  {path:<24} x{rec.get('calls', 0):<4} {rec.get('wall_s', 0.0):.4f}s  "
                f"{blk.get('dispatches', 0)} dispatches / "
                f"{blk.get('blocking_total', 0)} syncs  "
                f"collectives {rec.get('collective_counts', {})}",
                file=out,
            )
    tl = doc.get("timeline") or {}
    if tl:
        dropped = tl.get("events_dropped", 0)
        note = f" ({dropped} DROPPED past cap {tl.get('cap')})" if dropped else ""
        print(f"timeline: {tl.get('events', 0)} events{note}", file=out)
    for key in ("degraded", "faults", "io_retries", "checkpoint", "nonfinite", "retraces"):
        block = doc.get(key) or {}
        if block:
            print(f"{key}: {json.dumps(block, sort_keys=True)}", file=out)


# ----------------------------------------------------------------------
# memory: live ledger + watermark + per-program static peaks
# ----------------------------------------------------------------------
def _memory_doc(report_path: Optional[str], top: int) -> Dict[str, Any]:
    """The memory picture to render: a saved report's ``memory``/``programs``
    blocks when a path is given, else THIS process's live ledger (brings up
    the mesh and computes per-program costs — the interactive debug mode)."""
    if report_path is not None:
        doc = _load(report_path)
        return {
            "source": report_path,
            "memory": doc.get("memory") or {},
            "programs": doc.get("programs") or {},
        }
    import heat_tpu as ht  # noqa: F401 - the mesh must exist for a live ledger

    ht.get_comm()
    from heat_tpu.core import fusion, memledger

    return {
        "source": "<live>",
        "memory": {
            "ledger": memledger.ledger(top=top),
            "watermark": memledger.watermark(),
            "budget": memledger.budget_info(resolve=True),  # mesh is up here
            "last_oom": memledger.last_oom(),
        },
        "programs": {
            "cached": len(fusion.cache_stats()["program_keys"]),
            "cost_errors": fusion.cost_error_count(),
            "top": [
                dict(rec, key=key)
                for key, rec in fusion.program_costs(top=top).items()
            ],
        },
    }


def _show_memory(doc: Dict[str, Any], out) -> None:
    mem = doc.get("memory") or {}
    led = mem.get("ledger") or {}
    print(f"memory ({doc.get('source', '?')}):", file=out)
    if led:
        print(
            f"  live: {_fmt_bytes(led.get('total_bytes', 0))} over "
            f"{led.get('buffers', led.get('buffer_count', 0))} buffer(s)",
            file=out,
        )
        for owner, nbytes in sorted(
            (led.get("by_owner") or {}).items(), key=lambda kv: -kv[1]
        ):
            print(f"    {owner:<14} {_fmt_bytes(nbytes)}", file=out)
        for rec in led.get("top") or []:
            print(
                f"    top: {_fmt_bytes(rec.get('nbytes', 0)):<10} "
                f"{rec.get('owner', '?'):<14} {rec.get('dtype', '?')}"
                f"{rec.get('shape', [])}",
                file=out,
            )
    wm = mem.get("watermark") or {}
    if wm:
        print(
            f"  watermark: {_fmt_bytes(wm.get('bytes', 0))} "
            f"(event {wm.get('event')}, {wm.get('samples', 0)} samples) "
            f"{wm.get('by_owner', {})}",
            file=out,
        )
    budget = mem.get("budget") or {}
    if budget.get("budget") is not None:
        print(
            f"  budget: {_fmt_bytes(budget.get('budget_bytes'))} "
            f"policy={budget.get('policy')} checks={budget.get('checks', 0)} "
            f"exceeded={budget.get('exceeded', 0)} drains={budget.get('drains', 0)}",
            file=out,
        )
    oom = mem.get("last_oom")
    if oom:
        print(
            f"  LAST OOM: program {oom.get('program')} ({oom.get('family')}) "
            f"static peak {_fmt_bytes(oom.get('static_peak_bytes'))}, live "
            f"{_fmt_bytes(oom.get('live_total_bytes', 0))} by owner "
            f"{oom.get('by_owner', {})}",
            file=out,
        )
    dev = mem.get("device") or {}
    for name, stats in sorted(dev.items()):
        line = ", ".join(f"{k}={_fmt_bytes(v)}" for k, v in sorted(stats.items()))
        print(f"  {name}: {line}", file=out)
    progs = doc.get("programs") or {}
    top_progs = progs.get("top") or []
    if top_progs:
        print(
            f"per-program static peaks (of {progs.get('cached', 0)} cached, "
            f"{progs.get('cost_errors', 0)} cost error(s)):",
            file=out,
        )
        for rec in top_progs:
            memrec = (rec.get("cost") or rec).get("memory") or {}
            peak = memrec.get("peak_bytes")
            line = (
                f"  {rec.get('key', '?'):<18} x{rec.get('dispatches', 0):<6} "
                f"{str(rec.get('family', ''))[:48]:<48} "
            )
            if peak is not None:
                line += (
                    f"peak {_fmt_bytes(peak)} (args {_fmt_bytes(memrec.get('argument_bytes', 0))}"
                    f" + out {_fmt_bytes(memrec.get('output_bytes', 0))}"
                    f" + temp {_fmt_bytes(memrec.get('temp_bytes', 0))})"
                )
            else:
                line += "peak n/a"
            print(line, file=out)


# ----------------------------------------------------------------------
# health: flight recorder + watchdog + latency/SLO picture
# ----------------------------------------------------------------------
def _health_doc(report_path: Optional[str]) -> Dict[str, Any]:
    """The health picture to render: a saved report's (or flight-dump
    bundle's) ``health`` block when a path is given, else THIS process's
    live block — pure module state, no mesh bring-up (the never-initialize
    contract: asking for health must not pin a backend)."""
    if report_path is not None:
        doc = _load(report_path)
        blk = doc.get("health") or {}
        if not blk and "watchdog" in doc:  # a bare bundle without the block
            blk = {"watchdog": doc.get("watchdog") or {}}
        return {"source": report_path, "health": blk, "stalls": doc.get("stalls") or []}
    from heat_tpu.core import health_runtime

    return {
        "source": "<live>",
        "health": health_runtime.health_block(global_view=True),
        "stalls": health_runtime.stalls(),
    }


def _ms(v) -> str:
    try:
        return f"{float(v) * 1e3:.2f}ms"
    except (TypeError, ValueError):
        return "?"


def _show_health(doc: Dict[str, Any], out) -> None:
    blk = doc.get("health") or {}
    print(f"health ({doc.get('source', '?')}):", file=out)
    fl = blk.get("flight") or {}
    if fl:
        state = "armed" if fl.get("enabled") else "DISARMED"
        dropped = f", {fl['dropped']} dropped" if fl.get("dropped") else ""
        last = f"  last dump: {fl['last_dump']}" if fl.get("last_dump") else ""
        print(
            f"  flight: {state}, {fl.get('events', 0)}/{fl.get('cap', 0)} "
            f"events{dropped}, {fl.get('dumps', 0)} dump(s){last}",
            file=out,
        )
    wd = blk.get("watchdog") or {}
    if wd:
        state = "armed" if wd.get("enabled") else "DISARMED"
        print(
            f"  watchdog: {state}, deadline {wd.get('deadline_ms', 0)}ms "
            f"policy={wd.get('policy')} arms={wd.get('arms', 0)} "
            f"trips={wd.get('trips', 0)}",
            file=out,
        )
    for st in (doc.get("stalls") or [])[-3:]:
        print(
            f"  STALL: {st.get('site')} waited {st.get('waited_s')}s "
            f"(deadline {st.get('deadline_s')}s) program={st.get('program')} "
            f"pending={[r.get('cid') for r in st.get('pending_roots') or []]}",
            file=out,
        )
    for metric, title in (
        ("sync", "blocking-sync host wait"),
        ("dispatch", "dispatch→done"),
        ("compile", "compile time"),
    ):
        table = blk.get(metric) or {}
        rows = [(k, r) for k, r in table.items() if r.get("count")]
        if not rows:
            continue
        print(f"  {title}:", file=out)
        rows.sort(key=lambda kv: (kv[0] != "*", -kv[1].get("count", 0)))
        for key, rec in rows[:12]:
            print(
                f"    {key:<20} x{rec.get('count', 0):<6} "
                f"p50 {_ms(rec.get('p50_s'))}  p90 {_ms(rec.get('p90_s'))}  "
                f"p99 {_ms(rec.get('p99_s'))}  max {_ms(rec.get('max_s'))}",
                file=out,
            )
    slo = blk.get("slo") or {}
    for metric in ("sync", "dispatch", "compile"):
        rec = slo.get(metric) or {}
        if rec.get("limit_ms") is None:
            continue
        ratio = rec.get("ok_ratio")
        print(
            f"  SLO {metric}: limit {rec['limit_ms']}ms, {rec.get('recent', 0)} in "
            f"window, {rec.get('window_breaches', 0)} breach(es)"
            + (f", ok_ratio {ratio}" if ratio is not None else "")
            + f", {rec.get('breaches_total', 0)} total",
            file=out,
        )


# ----------------------------------------------------------------------
# diff
# ----------------------------------------------------------------------
def _flatten_numeric(doc, prefix="") -> Dict[str, float]:
    out: Dict[str, float] = {}
    if isinstance(doc, dict):
        for k, v in doc.items():
            out.update(_flatten_numeric(v, f"{prefix}{k}/" if prefix else f"{k}/"))
    elif isinstance(doc, bool) or doc is None or isinstance(doc, str):
        pass
    elif isinstance(doc, (int, float)):
        out[prefix.rstrip("/")] = float(doc)
    return out


def _diff(a: Dict[str, Any], b: Dict[str, Any], out, top: int = 40) -> int:
    """Print per-counter deltas b - a, largest absolute change first.
    Returns the number of changed counters."""
    fa, fb = _flatten_numeric(a), _flatten_numeric(b)
    deltas = []
    for key in sorted(set(fa) | set(fb)):
        if key.startswith("events/") or key.endswith("/ts"):
            continue  # raw timeline entries are not counters
        va, vb = fa.get(key, 0.0), fb.get(key, 0.0)
        if va != vb:
            deltas.append((abs(vb - va), key, va, vb))
    deltas.sort(reverse=True)
    for _, key, va, vb in deltas[:top]:
        sign = "+" if vb >= va else ""
        print(f"  {key:<64} {va:g} -> {vb:g} ({sign}{vb - va:g})", file=out)
    if len(deltas) > top:
        print(f"  ... and {len(deltas) - top} more changed counters", file=out)
    if not deltas:
        print("  no counter differences", file=out)
    return len(deltas)


# ----------------------------------------------------------------------
# entry point
# ----------------------------------------------------------------------
# ----------------------------------------------------------------------
# numerics: tensor stats + drift ledger + SDC canary + training streams
# ----------------------------------------------------------------------
def _sessions_doc(report_path: Optional[str]) -> Dict[str, Any]:
    """The serving picture to render: a saved report's ``serving`` block
    when a path is given, else THIS process's live block — pure module
    state, no mesh bring-up (the same never-initialize contract as
    ``health``/``numerics``)."""
    if report_path is not None:
        doc = _load(report_path)
        return {"source": report_path, "serving": doc.get("serving") or {}}
    from heat_tpu.core import serving

    return {"source": "<live>", "serving": serving.sessions_block()}


def _show_sessions(doc: Dict[str, Any], out) -> None:
    blk = doc.get("serving") or {}
    print(f"serving ({doc.get('source', '?')}):", file=out)
    sessions = blk.get("sessions") or []
    if not sessions:
        print("  no sessions recorded", file=out)
    adm = blk.get("admission") or {}
    gbl = adm.get("global")
    if gbl:
        print(
            f"  admission: policy {adm.get('policy', 'wait')}, global bucket "
            f"{gbl.get('rate')}/s burst {gbl.get('burst')} — "
            f"{gbl.get('admitted', 0)} admitted, {gbl.get('refused', 0)} "
            f"refused, {gbl.get('waited_s', 0)}s waited",
            file=out,
        )
    cache = blk.get("cache") or {}
    if cache.get("persistent_dir"):
        print(
            f"  persistent cache: {cache['persistent_dir']} "
            f"({cache.get('index_keys', 0)} indexed keys, "
            f"{cache.get('disk_hits', 0)} disk hits)",
            file=out,
        )
    for sess in sessions:
        st = sess.get("stats") or {}
        state = "active" if sess.get("active") else "exited"
        print(
            f"  {sess.get('name', '?')} ({state}): "
            f"{st.get('dispatches', 0)} dispatches "
            f"({st.get('roots', 0)} roots, {st.get('compiles', 0)} compiles), "
            f"errstate {sess.get('errstate', 'inherit')}, "
            f"numlens {sess.get('numlens', 'inherit')}",
            file=out,
        )
        trouble = {
            k: st.get(k, 0)
            for k in ("degraded", "quarantine_hits", "mem_refused",
                      "admission_refused", "admission_waits")
            if st.get(k)
        }
        if trouble:
            print(f"    incidents: {trouble}", file=out)
        if sess.get("quarantine"):
            print(f"    quarantine view: {sess['quarantine']}", file=out)
        bucket = sess.get("bucket")
        if bucket:
            print(
                f"    bucket: {bucket.get('rate')}/s burst {bucket.get('burst')} "
                f"— {bucket.get('admitted', 0)} admitted, "
                f"{bucket.get('refused', 0)} refused",
                file=out,
            )


# ----------------------------------------------------------------------
# ops: scrape / check / serve against a live ops-plane endpoint
# ----------------------------------------------------------------------
def _ops_base(args) -> str:
    if args.url:
        return args.url.rstrip("/")
    if args.port is None:
        raise SystemExit("ops: pass --url or --port to reach a live endpoint")
    return f"http://{args.host}:{int(args.port)}"


def _ops_get(url: str, timeout: float):
    """One GET: ``(status_code, body_text)`` — an HTTP error status is a
    result to report, not an exception."""
    import urllib.error
    import urllib.request

    try:
        with urllib.request.urlopen(url, timeout=timeout) as resp:
            return resp.status, resp.read().decode("utf-8", "replace")
    except urllib.error.HTTPError as exc:
        return exc.code, exc.read().decode("utf-8", "replace")


def _ops_scrape(args, out) -> int:
    url = _ops_base(args) + args.path
    try:
        code, body = _ops_get(url, args.timeout)
    except OSError as exc:
        print(f"ERROR: {url}: {exc}", file=out)
        return 1
    print(body, end="" if body.endswith("\n") else "\n", file=out)
    return 0 if code == 200 else 1


def _ops_check(args, out) -> int:
    """The strict endpoint check the test matrix runs mid-traffic: the
    ``/metrics`` exposition must validate (types, HELP lines, no duplicate
    samples, schema'd names only) and ``/healthz`` must answer 200."""
    from heat_tpu.core import opsplane

    base = _ops_base(args)
    rc = 0
    try:
        code, text = _ops_get(base + "/metrics", args.timeout)
    except OSError as exc:
        print(f"ERROR: {base}/metrics: {exc}", file=out)
        return 1
    if code != 200:
        print(f"FAIL: /metrics answered {code}", file=out)
        return 1
    problems = opsplane.validate_exposition(text)
    names = {
        line.split("{")[0].split()[0]
        for line in text.splitlines()
        if line and not line.startswith("#")
    }
    known = set(opsplane.SCHEMA)
    for mtype in ("histogram",):
        for name, spec in opsplane.SCHEMA.items():
            if spec[0] == mtype:
                known.update({name + s for s in ("_bucket", "_sum", "_count")})
    for name in sorted(names - known):
        problems.append(f"unschema'd metric name {name!r} (doc/metrics_schema.json)")
    if problems:
        for p in problems[:20]:
            print(f"INVALID: {p}", file=out)
        rc = 1
    else:
        samples = sum(
            1 for ln in text.splitlines() if ln and not ln.startswith("#")
        )
        print(
            f"OK: /metrics parses as Prometheus exposition "
            f"({len(names)} families, {samples} samples)",
            file=out,
        )
    try:
        code, body = _ops_get(base + "/healthz", args.timeout)
    except OSError as exc:
        print(f"ERROR: {base}/healthz: {exc}", file=out)
        return 1
    if code == 200:
        print("OK: /healthz answers 200", file=out)
    else:
        print(f"FAIL: /healthz answered {code}: {body.strip()[:200]}", file=out)
        rc = 1
    return rc


def _ops_serve(args, out) -> int:
    """Arm THIS process's ops plane and block — the sidecar-inspection
    entry (live module state; an idle CLI process exports mostly zeros,
    which is still a scrape target for wiring checks)."""
    import time as _time

    from heat_tpu.core import opsplane

    try:
        port = opsplane.serve(port=args.port, host=args.host)
    except ValueError as exc:
        print(f"ERROR: {exc}", file=out)
        return 2
    print(f"ops plane listening on http://{args.host}:{port}", file=out, flush=True)
    try:
        while True:
            _time.sleep(3600)
    except KeyboardInterrupt:
        opsplane.shutdown()
    return 0


def _numerics_doc(report_path: Optional[str]) -> Dict[str, Any]:
    """The numerics picture to render: a saved report's (or flight-dump
    bundle's) ``numerics`` block when a path is given, else THIS process's
    live block — pure module state, no mesh bring-up (the same
    never-initialize contract as ``health``)."""
    if report_path is not None:
        doc = _load(report_path)
        return {"source": report_path, "numerics": doc.get("numerics") or {}}
    from heat_tpu.core import numlens

    return {"source": "<live>", "numerics": numlens.numerics_block()}


def _show_numerics(doc: Dict[str, Any], out) -> None:
    blk = doc.get("numerics") or {}
    print(f"numerics ({doc.get('source', '?')}):", file=out)
    print(
        f"  lens: {blk.get('mode', 'off')}, sampled "
        f"{blk.get('dispatches_sampled', 0)}/{blk.get('dispatches_seen', 0)} "
        f"dispatches (every {blk.get('sample_every', '?')})",
        file=out,
    )
    stats = blk.get("tensor_stats") or {}
    if stats:
        print("  tensor stats:", file=out)
        rows = sorted(stats.items(), key=lambda kv: -kv[1].get("samples", 0))
        for key, rec in rows[:8]:
            for i, rr in sorted((rec.get("roots") or {}).items()):
                flags = []
                if rr.get("nonfinite"):
                    flags.append(f"NONFINITE x{rr['nonfinite']}")
                if rr.get("subnormal"):
                    flags.append(f"subnormal {rr.get('subnormal_pct', 0)}%")
                if rr.get("edge_high"):
                    flags.append(f"edge_high {rr['edge_high']}")
                print(
                    f"    {key}[{i}] {rr.get('dtype')}  rms {rr.get('rms', 0):.4g}  "
                    f"absmax {rr.get('absmax', 0):.4g}  x{rr.get('samples', 0)}"
                    + ("  " + " ".join(flags) if flags else ""),
                    file=out,
                )
    drift = blk.get("drift") or {}
    progs = drift.get("programs") or {}
    if progs:
        print(
            f"  drift ledger (max {drift.get('max_ulp', 0)} ULP, worst family "
            f"{drift.get('worst_family')}):",
            file=out,
        )
        for key, rec in sorted(progs.items(), key=lambda kv: -kv[1].get("max_ulp", 0))[:8]:
            print(
                f"    {key}  p50 {rec.get('p50_ulp', 0)} ULP  max "
                f"{rec.get('max_ulp', 0)} ULP  x{rec.get('samples', 0)}",
                file=out,
            )
    canary = blk.get("canary") or {}
    if canary.get("runs"):
        sick = canary.get("last_sick") or []
        print(
            f"  sdc canary: {canary['runs']} run(s) over "
            f"{canary.get('devices', '?')} device(s), "
            f"{canary.get('mismatches', 0)} mismatch(es), last "
            f"{canary.get('last_ms', '?')}ms"
            + (f"  SICK: {', '.join(sick)}" if sick else ""),
            file=out,
        )
    for tag, rec in (blk.get("training") or {}).items():
        extras = []
        if rec.get("overflows"):
            extras.append(f"OVERFLOWS x{rec['overflows']}")
        if rec.get("plateau"):
            extras.append("PLATEAU")
        ratio = rec.get("last_update_ratio")
        print(
            f"  train[{tag}]: {rec.get('steps', 0)} step(s), loss "
            f"{rec.get('last_loss')}"
            + (f", update_ratio {ratio:.3g}" if ratio is not None else "")
            + ("  " + " ".join(extras) if extras else ""),
            file=out,
        )
    for f in (blk.get("findings") or [])[-5:]:
        print(f"  {f.get('severity', '?').upper()}: {f.get('message')}", file=out)


def main(argv: Optional[List[str]] = None, out=None) -> int:
    out = out if out is not None else sys.stdout
    parser = argparse.ArgumentParser(
        prog="python -m heat_tpu.telemetry",
        description="Pretty-print/diff heat_tpu telemetry reports and validate trace files.",
    )
    sub = parser.add_subparsers(dest="cmd", required=True)
    p_show = sub.add_parser("show", help="pretty-print a report_json artifact")
    p_show.add_argument("report", help="path to a telemetry report_json file")
    p_show.add_argument("--raw", action="store_true", help="re-emit the parsed JSON instead")
    p_diff = sub.add_parser("diff", help="diff two report_json artifacts (b - a)")
    p_diff.add_argument("a")
    p_diff.add_argument("b")
    p_mem = sub.add_parser(
        "memory",
        help="live-buffer ledger + watermark + per-program static peaks "
        "(from a report_json artifact, or live from this process)",
    )
    p_mem.add_argument(
        "report",
        nargs="?",
        default=None,
        help="a report_json artifact; omitted = sample THIS process live "
        "(brings up the mesh)",
    )
    p_mem.add_argument("--json", action="store_true", help="emit JSON instead of text")
    p_mem.add_argument("--top", type=int, default=5, help="top-K buffers/programs shown")
    p_health = sub.add_parser(
        "health",
        help="runtime health: flight recorder, watchdog/stalls, latency "
        "p50/p90/p99 and SLO gauges (from a report_json artifact or a "
        "flight-dump bundle, or live from this process)",
    )
    p_health.add_argument(
        "report",
        nargs="?",
        default=None,
        help="a report_json artifact or flight-dump bundle; omitted = THIS "
        "process's live health block (pure module state, no mesh bring-up)",
    )
    p_health.add_argument("--json", action="store_true", help="emit JSON instead of text")
    p_num = sub.add_parser(
        "numerics",
        help="numerics lens: streaming tensor stats, shadow-replay drift "
        "ledger, SDC canary summary and training-signal streams (from a "
        "report_json artifact or a flight-dump bundle, or live from this "
        "process)",
    )
    p_num.add_argument(
        "report",
        nargs="?",
        default=None,
        help="a report_json artifact or flight-dump bundle; omitted = THIS "
        "process's live numerics block (pure module state, no mesh bring-up)",
    )
    p_num.add_argument("--json", action="store_true", help="emit JSON instead of text")
    p_sess = sub.add_parser(
        "sessions",
        help="serving layer: per-session billing/incident blocks, admission "
        "buckets and the persistent program cache (from a report_json "
        "artifact, or live from this process)",
    )
    p_sess.add_argument(
        "report",
        nargs="?",
        default=None,
        help="a report_json artifact; omitted = THIS process's live serving "
        "block (pure module state, no mesh bring-up)",
    )
    p_sess.add_argument("--json", action="store_true", help="emit JSON instead of text")
    p_ana = sub.add_parser(
        "analyze",
        help="tracelens diagnosis of a trace: time attribution per bucket, "
        "critical path, cross-host straggler attribution, anti-pattern "
        "findings; nonzero exit on warning/error findings or on regression "
        "vs --against",
    )
    p_ana.add_argument(
        "trace",
        nargs="?",
        default=None,
        help="an export_trace/merge_traces file; omitted = THIS process's "
        "live verbose timeline",
    )
    p_ana.add_argument(
        "--against",
        default=None,
        help="baseline to diff against: a saved `analyze --json` output or "
        "another trace file (bucket shifts, new findings, critical-path "
        "growth; regressions exit 1)",
    )
    p_ana.add_argument("--json", action="store_true", help="emit JSON instead of text")
    p_ana.add_argument(
        "--allow-partial",
        action="store_true",
        help="analyze a window with dropped events anyway (attribution "
        "undercounts the evicted prefix; refused with exit 2 otherwise)",
    )
    p_ops = sub.add_parser(
        "ops",
        help="live ops plane: scrape an endpoint, strict-check its "
        "/metrics exposition + /healthz, or serve this process's plane",
    )
    p_ops.add_argument("action", choices=("scrape", "check", "serve"))
    p_ops.add_argument("--url", default=None, help="endpoint base URL (overrides --host/--port)")
    p_ops.add_argument("--host", default="127.0.0.1")
    p_ops.add_argument("--port", type=int, default=None)
    p_ops.add_argument(
        "--path", default="/metrics", help="route for 'scrape' (default /metrics)"
    )
    p_ops.add_argument("--timeout", type=float, default=10.0)
    p_val = sub.add_parser(
        "validate-trace", help="check a Chrome/Perfetto trace-event JSON file"
    )
    p_val.add_argument("trace", help="path to an export_trace/merge_traces output")
    p_val.add_argument(
        "--cross-host",
        action="store_true",
        help="also require cross-host collective parity (per-cid collective event "
        "counts equal on every process row — the runtime signature of an H001 "
        "deadlock when violated)",
    )
    args = parser.parse_args(argv)

    if args.cmd == "show":
        doc = _load(args.report)
        if args.raw:
            print(json.dumps(doc, indent=2, sort_keys=True), file=out)
        else:
            _show(doc, out)
        return 0
    if args.cmd == "diff":
        _diff(_load(args.a), _load(args.b), out)
        return 0
    if args.cmd == "memory":
        doc = _memory_doc(args.report, top=args.top)
        if args.json:
            print(json.dumps(_core._jsonable(doc), indent=2, sort_keys=True), file=out)
        else:
            _show_memory(doc, out)
        return 0
    if args.cmd == "health":
        doc = _health_doc(args.report)
        if args.json:
            print(json.dumps(_core._jsonable(doc), indent=2, sort_keys=True), file=out)
        else:
            _show_health(doc, out)
        return 0
    if args.cmd == "numerics":
        doc = _numerics_doc(args.report)
        if args.json:
            print(json.dumps(_core._jsonable(doc), indent=2, sort_keys=True), file=out)
        else:
            _show_numerics(doc, out)
        return 0
    if args.cmd == "sessions":
        doc = _sessions_doc(args.report)
        if args.json:
            print(json.dumps(_core._jsonable(doc), indent=2, sort_keys=True), file=out)
        else:
            _show_sessions(doc, out)
        return 0
    if args.cmd == "ops":
        if args.action == "scrape":
            return _ops_scrape(args, out)
        if args.action == "check":
            return _ops_check(args, out)
        return _ops_serve(args, out)
    if args.cmd == "analyze":
        from heat_tpu.core import tracelens

        try:
            analysis = tracelens.analyze(args.trace, allow_partial=args.allow_partial)
        except tracelens.TraceIncompleteError as exc:
            print(f"REFUSED: {exc}", file=out)
            return 2
        except (ValueError, OSError) as exc:
            print(f"ERROR: {exc}", file=out)
            return 2
        delta = None
        if args.against is not None:
            try:
                baseline = tracelens.load_analysis(args.against)
            except (ValueError, OSError) as exc:
                print(f"ERROR: cannot load baseline: {exc}", file=out)
                return 2
            delta = tracelens.diff(baseline, analysis)
        if args.json:
            doc = dict(analysis)
            if delta is not None:
                doc["against"] = delta
            print(json.dumps(_core._jsonable(doc), indent=2, sort_keys=True), file=out)
        else:
            print(tracelens.render(analysis), file=out)
            if delta is not None:
                shifts = delta["bucket_shifts_pts"]
                if shifts:
                    print("vs baseline (bucket shifts, pts):", file=out)
                    for bucket, pts in sorted(shifts.items(), key=lambda kv: -abs(kv[1])):
                        print(f"  {bucket:<16} {pts:+.2f}", file=out)
                for f in delta["new_findings"]:
                    print(
                        f"NEW [{f.get('severity', '?')}] {f.get('rule')}: "
                        f"{f.get('message')}",
                        file=out,
                    )
                for r in delta["regressions"]:
                    print(f"REGRESSION: {r}", file=out)
        gate = any(
            f.get("severity") in ("error", "warning") for f in analysis["findings"]
        )
        if delta is not None and not delta["ok"]:
            gate = True
        return 1 if gate else 0
    if args.cmd == "validate-trace":
        problems = _core.validate_trace(args.trace, cross_host=args.cross_host)
        if problems:
            for p in problems[:20]:
                print(f"INVALID: {p}", file=out)
            return 1
        with open(args.trace) as fh:
            n = len(json.load(fh).get("traceEvents", []))
        parity = " + cross-host collective parity" if args.cross_host else ""
        print(
            f"OK: {args.trace} parses as trace-event JSON ({n} events){parity}",
            file=out,
        )
        return 0
    return 2  # pragma: no cover - argparse enforces the subcommands


if __name__ == "__main__":  # pragma: no cover - exercised via subprocess in CI
    sys.exit(main())
